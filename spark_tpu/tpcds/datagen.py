"""Deterministic scaled-down TPC-DS data generator.

Not dsdgen: a seeded numpy generator producing referentially-consistent
tables with the official columns and value domains the query set filters
on (categories, demographics bands, calendar).  Correctness testing needs
an oracle on the SAME data (sqlite / pandas), so official distributions
are unnecessary; sizes scale linearly with ``sf_rows``.

Returns sampled from sales keep the (item, ticket/order, customer) join
identity the 3-channel queries (q17/q25/q29...) rely on.
"""

from __future__ import annotations

import datetime
from typing import Dict

import numpy as np
import pandas as pd

from .schema import TABLES

CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
              "Men", "Music", "Shoes", "Sports", "Women"]
CLASSES = ["accent", "bedding", "classical", "dresses", "estate",
           "fiction", "fitness", "pants", "portable", "romance"]
DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
             "Friday", "Saturday"]
BUY_POTENTIAL = [">10000", "5001-10000", "1001-5000", "501-1000",
                 "0-500", "Unknown"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree",
             "4 yr Degree", "Advanced Degree", "Unknown"]
CREDIT_RATING = ["Low Risk", "Good", "High Risk", "Unknown"]
STATES = ["TN", "CA", "TX", "NY", "OH", "GA", "IL", "WA", "MI", "NC"]
COUNTIES = ["Williamson County", "Walker County", "Ziebach County",
            "Bronx County", "Franklin Parish"]
SM_TYPES = ["EXPRESS", "NEXT DAY", "OVERNIGHT", "REGULAR", "LIBRARY"]
SM_CARRIERS = ["UPS", "FEDEX", "AIRBORNE", "USPS", "DHL"]

DATE0_SK = 2450815            # 1998-01-01, official julian-style origin
DATE0 = datetime.date(1998, 1, 1)
N_DAYS = 5 * 365 + 1          # 1998-01-01 .. 2002-12-30


def _date_dim() -> pd.DataFrame:
    days = np.arange(N_DAYS)
    dates = [DATE0 + datetime.timedelta(days=int(i)) for i in days]
    yy = np.array([d.year for d in dates], np.int32)
    mm = np.array([d.month for d in dates], np.int32)
    dd = np.array([d.day for d in dates], np.int32)
    dow = np.array([(d.weekday() + 1) % 7 for d in dates], np.int32)  # 0=Sun
    qoy = (mm - 1) // 3 + 1
    month_seq = (yy - 1900) * 12 + (mm - 1)
    week_seq = (days + (DATE0.weekday() + 1) % 7) // 7 + 5112
    return pd.DataFrame({
        "d_date_sk": DATE0_SK + days,
        "d_date_id": [f"AAAAAAAA{sk:08d}" for sk in DATE0_SK + days],
        "d_date": [d.isoformat() for d in dates],
        "d_month_seq": month_seq,
        "d_week_seq": week_seq.astype(np.int32),
        "d_quarter_seq": (yy - 1900) * 4 + qoy - 1,
        "d_year": yy, "d_dow": dow, "d_moy": mm, "d_dom": dd, "d_qoy": qoy,
        "d_fy_year": yy, "d_fy_quarter_seq": (yy - 1900) * 4 + qoy - 1,
        "d_fy_week_seq": week_seq.astype(np.int32),
        "d_day_name": [DAY_NAMES[x] for x in dow],
        "d_quarter_name": [f"{y}Q{q}" for y, q in zip(yy, qoy)],
        "d_holiday": np.where((mm == 12) & (dd == 25), "Y", "N"),
        "d_weekend": np.where((dow == 0) | (dow == 6), "Y", "N"),
        "d_following_holiday": "N",
        "d_first_dom": (DATE0_SK + days - dd + 1).astype(np.int64),
        "d_last_dom": (DATE0_SK + days - dd + 28).astype(np.int64),
        "d_same_day_ly": DATE0_SK + days - 365,
        "d_same_day_lq": DATE0_SK + days - 91,
        "d_current_day": "N", "d_current_week": "N", "d_current_month": "N",
        "d_current_quarter": "N", "d_current_year": "N",
    })


def _time_dim() -> pd.DataFrame:
    t = np.arange(86400)
    hh, rem = t // 3600, t % 3600
    return pd.DataFrame({
        "t_time_sk": t.astype(np.int64),
        "t_time_id": [f"AAAAAAAA{x:08d}" for x in t],
        "t_time": t.astype(np.int32),
        "t_hour": hh.astype(np.int32),
        "t_minute": (rem // 60).astype(np.int32),
        "t_second": (rem % 60).astype(np.int32),
        "t_am_pm": np.where(hh < 12, "AM", "PM"),
        "t_shift": np.where(hh < 8, "third",
                            np.where(hh < 16, "first", "second")),
        "t_sub_shift": np.where(hh < 6, "night",
                                np.where(hh < 12, "morning",
                                         np.where(hh < 18, "afternoon",
                                                  "evening"))),
        "t_meal_time": np.where((hh >= 6) & (hh < 9), "breakfast",
                                np.where((hh >= 11) & (hh < 14), "lunch",
                                         np.where((hh >= 17) & (hh < 20),
                                                  "dinner", None))),
    })


def _items(rng, n) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    cat_id = rng.integers(1, 11, n)
    # classes NEST within categories (3 per category), as in dsdgen's
    # hierarchy — category and class are correlated, so conjunctive
    # filters like q54's (i_category AND i_class) select real item sets.
    # The raw draw keeps the SAME rng stream shape as the historical
    # independent draw, so every downstream column (manufact, manager,
    # colors...) and the fixed query parameters keyed to them survive.
    class_raw = rng.integers(1, 11, n)
    class_id = ((cat_id - 1) * 3 + class_raw % 3) % 10 + 1
    manufact = rng.integers(1, 101, n)
    brand_id = cat_id * 1000000 + class_id * 10000 + rng.integers(1, 100, n)
    manager = rng.integers(1, 101, n)
    return pd.DataFrame({
        "i_item_sk": sk.astype(np.int64),
        "i_item_id": [f"AAAAAAAA{x:08d}" for x in sk],
        "i_rec_start_date": "1997-10-27", "i_rec_end_date": None,
        "i_item_desc": [f"item description {x}" for x in sk],
        "i_current_price": np.round(rng.uniform(0.5, 100.0, n), 2),
        "i_wholesale_cost": np.round(rng.uniform(0.3, 80.0, n), 2),
        "i_brand_id": brand_id.astype(np.int32),
        "i_brand": [f"brand#{b}" for b in brand_id],
        "i_class_id": class_id.astype(np.int32),
        "i_class": [CLASSES[c - 1] for c in class_id],
        "i_category_id": cat_id.astype(np.int32),
        "i_category": [CATEGORIES[c - 1] for c in cat_id],
        "i_manufact_id": manufact.astype(np.int32),
        "i_manufact": [f"manufact#{m}" for m in manufact],
        "i_size": rng.choice(["small", "medium", "large", "extra large",
                              "economy", "N/A", "petite"], n),
        "i_formulation": [f"formulation {x}" for x in rng.integers(0, 100, n)],
        "i_color": rng.choice(["red", "blue", "green", "white", "black",
                               "navy", "peru", "saddle", "powder"], n),
        "i_units": rng.choice(["Each", "Dozen", "Case", "Pallet", "Oz",
                               "Lb", "Ton", "Gram"], n),
        "i_container": "Unknown",
        "i_manager_id": manager.astype(np.int32),
        "i_product_name": [f"product {x}" for x in sk],
    })


def _customers(rng, n, n_addr, n_cdemo, n_hdemo) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    by = rng.integers(1924, 1993, n)
    return pd.DataFrame({
        "c_customer_sk": sk.astype(np.int64),
        "c_customer_id": [f"AAAAAAAA{x:08d}" for x in sk],
        "c_current_cdemo_sk": rng.integers(1, n_cdemo + 1, n).astype(np.int64),
        "c_current_hdemo_sk": rng.integers(1, n_hdemo + 1, n).astype(np.int64),
        "c_current_addr_sk": rng.integers(1, n_addr + 1, n).astype(np.int64),
        "c_first_shipto_date_sk": DATE0_SK + rng.integers(0, N_DAYS, n),
        "c_first_sales_date_sk": DATE0_SK + rng.integers(0, N_DAYS, n),
        "c_salutation": rng.choice(["Mr.", "Mrs.", "Ms.", "Dr.", "Miss",
                                    "Sir"], n),
        "c_first_name": rng.choice(["James", "Mary", "John", "Linda",
                                    "Robert", "Ann", "Jose", "Lily"], n),
        "c_last_name": rng.choice(["Smith", "Jones", "Brown", "Lee",
                                   "Wilson", "Garcia", "Miller"], n),
        "c_preferred_cust_flag": rng.choice(["Y", "N"], n),
        "c_birth_day": rng.integers(1, 29, n).astype(np.int32),
        "c_birth_month": rng.integers(1, 13, n).astype(np.int32),
        "c_birth_year": by.astype(np.int32),
        "c_birth_country": rng.choice(["UNITED STATES", "CANADA", "MEXICO",
                                       "FRANCE", "JAPAN"], n),
        "c_login": None,
        "c_email_address": [f"c{x}@example.com" for x in sk],
        "c_last_review_date": None,
    })


def _addresses(rng, n) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    return pd.DataFrame({
        "ca_address_sk": sk.astype(np.int64),
        "ca_address_id": [f"AAAAAAAA{x:08d}" for x in sk],
        "ca_street_number": [str(x) for x in rng.integers(1, 1000, n)],
        "ca_street_name": rng.choice(["Main", "Oak", "First", "Park",
                                      "Cedar", "Elm"], n),
        "ca_street_type": rng.choice(["St", "Ave", "Blvd", "Way", "Dr"], n),
        "ca_suite_number": [f"Suite {x}" for x in rng.integers(0, 100, n)],
        "ca_city": rng.choice(["Fairview", "Midway", "Oak Grove",
                               "Centerville", "Riverside", "Salem"], n),
        "ca_county": rng.choice(COUNTIES, n),
        "ca_state": rng.choice(STATES, n),
        "ca_zip": [f"{x:05d}" for x in
           rng.choice(rng.integers(10000, 99999, 200), n)],
        "ca_country": "United States",
        "ca_gmt_offset": rng.choice([-5.0, -6.0, -7.0, -8.0], n),
        "ca_location_type": rng.choice(["apartment", "condo",
                                        "single family"], n),
    })


def _cdemo(n) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    return pd.DataFrame({
        "cd_demo_sk": sk.astype(np.int64),
        "cd_gender": np.where(sk % 2 == 0, "F", "M"),
        "cd_marital_status": np.array(["M", "S", "D", "W", "U"])[sk % 5],
        "cd_education_status": np.array(EDUCATION)[sk % 7],
        "cd_purchase_estimate": ((sk % 20) * 500 + 500).astype(np.int32),
        "cd_credit_rating": np.array(CREDIT_RATING)[sk % 4],
        "cd_dep_count": (sk % 7).astype(np.int32),
        "cd_dep_employed_count": (sk % 7).astype(np.int32),
        "cd_dep_college_count": (sk % 7).astype(np.int32),
    })


def _hdemo(n) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    return pd.DataFrame({
        "hd_demo_sk": sk.astype(np.int64),
        "hd_income_band_sk": (sk % 20 + 1).astype(np.int64),
        "hd_buy_potential": np.array(BUY_POTENTIAL)[sk % 6],
        "hd_dep_count": (sk % 10).astype(np.int32),
        "hd_vehicle_count": (sk % 6 - 1).astype(np.int32),
    })


def _stores(rng, n, zips=None) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    return pd.DataFrame({
        "s_store_sk": sk.astype(np.int64),
        "s_store_id": [f"AAAAAAAA{x:08d}" for x in sk],
        "s_rec_start_date": "1997-03-13", "s_rec_end_date": None,
        "s_closed_date_sk": None,
        "s_store_name": rng.choice(["ought", "able", "pri", "ese", "anti",
                                    "cally", "ation", "eing"], n),
        "s_number_employees": rng.integers(200, 300, n).astype(np.int32),
        "s_floor_space": rng.integers(5000000, 10000000, n).astype(np.int32),
        "s_hours": rng.choice(["8AM-8AM", "8AM-4PM", "8AM-12AM"], n),
        "s_manager": [f"Manager {x}" for x in rng.integers(1, 50, n)],
        "s_market_id": rng.integers(1, 11, n).astype(np.int32),
        "s_geography_class": "Unknown",
        "s_market_desc": [f"market {x}" for x in rng.integers(0, 50, n)],
        "s_market_manager": [f"Mkt Manager {x}"
                             for x in rng.integers(1, 50, n)],
        "s_division_id": np.ones(n, np.int32),
        "s_division_name": "Unknown",
        "s_company_id": np.ones(n, np.int32),
        "s_company_name": "Unknown",
        "s_street_number": [str(x) for x in rng.integers(1, 1000, n)],
        "s_street_name": rng.choice(["Main", "Oak", "First"], n),
        "s_street_type": rng.choice(["St", "Ave", "Blvd"], n),
        "s_suite_number": [f"Suite {x}" for x in rng.integers(0, 100, n)],
        "s_city": rng.choice(["Fairview", "Midway"], n),
        "s_county": rng.choice(COUNTIES, n),
        "s_state": rng.choice(STATES[:5], n),
        # store zips come from the address zip pool when provided: spec
        # queries (q24) join stores to customer addresses on zip equality
        "s_zip": (list(rng.choice(zips, n)) if zips is not None
                  else [f"{x:05d}" for x in rng.integers(10000, 99999, n)]),
        "s_country": "United States",
        "s_gmt_offset": rng.choice([-5.0, -6.0], n),
        "s_tax_precentage": np.round(rng.uniform(0.0, 0.11, n), 2),
    })


def _promotions(rng, n, n_items) -> pd.DataFrame:
    sk = np.arange(1, n + 1)
    flags = lambda: rng.choice(["Y", "N"], n)  # noqa: E731
    return pd.DataFrame({
        "p_promo_sk": sk.astype(np.int64),
        "p_promo_id": [f"AAAAAAAA{x:08d}" for x in sk],
        "p_start_date_sk": DATE0_SK + rng.integers(0, N_DAYS, n),
        "p_end_date_sk": DATE0_SK + rng.integers(0, N_DAYS, n),
        "p_item_sk": rng.integers(1, n_items + 1, n).astype(np.int64),
        "p_cost": 1000.0,
        "p_response_target": np.ones(n, np.int32),
        "p_promo_name": rng.choice(["ought", "able", "pri", "ese"], n),
        "p_channel_dmail": flags(), "p_channel_email": flags(),
        "p_channel_catalog": flags(), "p_channel_tv": flags(),
        "p_channel_radio": flags(), "p_channel_press": flags(),
        "p_channel_event": flags(), "p_channel_demo": flags(),
        "p_channel_details": [f"promo details {x}" for x in sk],
        "p_purpose": "Unknown",
        "p_discount_active": flags(),
    })


class SkewDists:
    """dsdgen-like marginals for the sales facts (VERDICT r3 item 7):

    * Zipf(alpha) item/customer popularity over PERMUTED domains (hot
      ids scattered, not clustered at low sks),
    * a few hot stores,
    * seasonal dates (holiday-quarter ramp + weekend lift),
    * item-category price levels (price correlates with category).

    Uniform generation remains the default (``skew=None``)."""

    def __init__(self, rng, alpha, n_items, n_cust, n_store, date_n,
                 item_cat_ids, date_moy, date_dow):
        self.rng = rng
        self._items = self._zipf(n_items, alpha)
        self._custs = self._zipf(n_cust, alpha)
        self._stores = self._zipf(n_store, max(alpha * 0.75, 0.5))
        dow = date_dow[:date_n]
        w = (1.0 + 1.5 * (date_moy[:date_n] >= 11)
             + 0.3 * ((dow == 0) | (dow == 6)))   # 0=Sun, 6=Sat

        self._date_w = w / w.sum()
        self.date_n = date_n
        # category price level: Books cheap → Jewelry dear, 0.6x..1.5x
        self.price_mult = 0.6 + 0.1 * item_cat_ids.astype(np.float64)

    def _zipf(self, domain_n, alpha):
        ranks = np.arange(1, domain_n + 1, dtype=np.float64)
        w = ranks ** -alpha
        w /= w.sum()
        perm = self.rng.permutation(domain_n)
        return (w, perm)

    def _draw(self, spec, n):
        w, perm = spec
        return (perm[self.rng.choice(len(w), size=n, p=w)] + 1
                ).astype(np.int64)

    def items(self, n):
        return self._draw(self._items, n)

    def customers(self, n):
        return self._draw(self._custs, n)

    def stores(self, n):
        return self._draw(self._stores, n)

    def dates(self, n):
        return self.rng.choice(self.date_n, size=n, p=self._date_w)


def _sales(rng, n, pre, date_n, n_items, n_cust, n_addr, n_cdemo, n_hdemo,
           n_store, n_promo, with_ship=False, extra=None,
           dists: "SkewDists | None" = None) -> pd.DataFrame:
    """Generic sales fact; `pre` is the column prefix data ('ss'...)."""
    qty = rng.integers(1, 101, n)
    # skewed draws happen up front; the UNIFORM path must draw item_sk at
    # its historical position inside the dict below — the rng stream
    # shape is load-bearing (fixed query parameters key to it)
    item_sk = dists.items(n) if dists is not None else None
    wholesale = np.round(rng.uniform(1.0, 100.0, n), 2)
    if dists is not None:
        wholesale = np.round(wholesale * dists.price_mult[item_sk - 1], 2)
    list_price = np.round(wholesale * rng.uniform(1.0, 2.0, n), 2)
    sales_price = np.round(list_price * rng.uniform(0.2, 1.0, n), 2)
    ext_discount = np.round((list_price - sales_price) * qty, 2)
    ext_sales = np.round(sales_price * qty, 2)
    ext_wholesale = np.round(wholesale * qty, 2)
    ext_list = np.round(list_price * qty, 2)
    ext_tax = np.round(ext_sales * 0.08, 2)
    coupon = np.round(ext_sales * rng.choice([0.0, 0.0, 0.0, 0.1], n), 2)
    net_paid = np.round(ext_sales - coupon, 2)
    net_paid_tax = np.round(net_paid + ext_tax, 2)
    profit = np.round(net_paid - ext_wholesale, 2)
    sold_date = DATE0_SK + (dists.dates(n) if dists is not None
                            else rng.integers(0, date_n, n))

    def null_some(arr, frac=0.04):
        a = arr.astype(object)
        mask = rng.random(n) < frac
        a[mask] = None
        return a

    base = {
        "sold_date_sk": null_some(sold_date),
        "sold_time_sk": rng.integers(0, 86400, n).astype(np.int64),
        "item_sk": (item_sk if item_sk is not None
                    else rng.integers(1, n_items + 1, n).astype(np.int64)),
        "customer_sk": null_some(
            dists.customers(n) if dists is not None
            else rng.integers(1, n_cust + 1, n)),
        "cdemo_sk": rng.integers(1, n_cdemo + 1, n).astype(np.int64),
        "hdemo_sk": rng.integers(1, n_hdemo + 1, n).astype(np.int64),
        "addr_sk": rng.integers(1, n_addr + 1, n).astype(np.int64),
        "store_sk": null_some(
            dists.stores(n) if dists is not None
            else rng.integers(1, n_store + 1, n)),
        "promo_sk": rng.integers(1, n_promo + 1, n).astype(np.int64),
        "ticket_number": np.arange(1, n + 1, dtype=np.int64),
        "quantity": qty.astype(np.int32),
        "wholesale_cost": wholesale, "list_price": list_price,
        "sales_price": sales_price, "ext_discount_amt": ext_discount,
        "ext_sales_price": ext_sales, "ext_wholesale_cost": ext_wholesale,
        "ext_list_price": ext_list, "ext_tax": ext_tax, "coupon_amt": coupon,
        "net_paid": net_paid, "net_paid_inc_tax": net_paid_tax,
        "net_profit": profit,
    }
    if extra:
        base.update(extra(rng, n, sold_date))
    return base


def generate(sf_rows: int = 40_000, seed: int = 20260729,
             skew: "float | None" = None,
             measure_null_frac: float = 0.0) -> Dict[str, pd.DataFrame]:
    """All 24 tables; `sf_rows` sizes store_sales, other facts scale off it.

    ``skew`` switches the fact marginals from uniform to dsdgen-like
    (Zipf item/customer/store popularity, seasonal dates, category price
    levels — see SkewDists); ``measure_null_frac`` additionally NULLs a
    fraction of the price/quantity measures on the sales facts."""
    rng = np.random.default_rng(seed)
    n_items, n_cust, n_addr = 1000, 2000, 1000
    n_cdemo, n_hdemo, n_store, n_promo = 1920, 720, 12, 300
    n_wh, n_cc, n_web, n_wp, n_cp = 5, 6, 12, 60, 120

    out: Dict[str, pd.DataFrame] = {}
    out["date_dim"] = _date_dim()
    out["time_dim"] = _time_dim()
    out["item"] = _items(rng, n_items)
    out["customer"] = _customers(rng, n_cust, n_addr, n_cdemo, n_hdemo)
    out["customer_address"] = _addresses(rng, n_addr)
    out["customer_demographics"] = _cdemo(n_cdemo)
    out["household_demographics"] = _hdemo(n_hdemo)
    ib = np.arange(1, 21)
    out["income_band"] = pd.DataFrame({
        "ib_income_band_sk": ib.astype(np.int64),
        "ib_lower_bound": ((ib - 1) * 10000).astype(np.int32),
        "ib_upper_bound": (ib * 10000).astype(np.int32)})
    out["store"] = _stores(
        rng, n_store, zips=out["customer_address"]["ca_zip"].values)
    out["promotion"] = _promotions(rng, n_promo, n_items)
    sm = np.arange(1, 21)
    out["ship_mode"] = pd.DataFrame({
        "sm_ship_mode_sk": sm.astype(np.int64),
        "sm_ship_mode_id": [f"AAAAAAAA{x:08d}" for x in sm],
        "sm_type": np.array(SM_TYPES)[sm % 5],
        "sm_code": np.array(["AIR", "SURFACE", "SEA"])[sm % 3],
        "sm_carrier": np.array(SM_CARRIERS)[sm % 5],
        "sm_contract": [f"contract {x}" for x in sm]})
    rr = np.arange(1, 36)
    out["reason"] = pd.DataFrame({
        "r_reason_sk": rr.astype(np.int64),
        "r_reason_id": [f"AAAAAAAA{x:08d}" for x in rr],
        "r_reason_desc": [f"reason {x}" for x in rr]})
    wh = np.arange(1, n_wh + 1)
    out["warehouse"] = pd.DataFrame({
        "w_warehouse_sk": wh.astype(np.int64),
        "w_warehouse_id": [f"AAAAAAAA{x:08d}" for x in wh],
        "w_warehouse_name": [f"Warehouse number {x}" for x in wh],
        "w_warehouse_sq_ft": (wh * 100000).astype(np.int32),
        "w_street_number": "501", "w_street_name": "Main",
        "w_street_type": "St", "w_suite_number": "Suite 0",
        "w_city": "Fairview", "w_county": COUNTIES[0], "w_state": "TN",
        "w_zip": "35709", "w_country": "United States",
        "w_gmt_offset": -5.0})
    cc = np.arange(1, n_cc + 1)
    out["call_center"] = pd.DataFrame({
        "cc_call_center_sk": cc.astype(np.int64),
        "cc_call_center_id": [f"AAAAAAAA{x:08d}" for x in cc],
        "cc_rec_start_date": "1998-01-01", "cc_rec_end_date": None,
        "cc_closed_date_sk": None, "cc_open_date_sk": DATE0_SK,
        "cc_name": [f"call center {x}" for x in cc],
        "cc_class": "medium", "cc_employees": (cc * 100).astype(np.int32),
        "cc_sq_ft": (cc * 1000).astype(np.int32), "cc_hours": "8AM-8AM",
        "cc_manager": [f"Manager {x}" for x in cc],
        "cc_mkt_id": (cc % 6 + 1).astype(np.int32), "cc_mkt_class": "Unknown",
        "cc_mkt_desc": "Unknown", "cc_market_manager": "Unknown",
        "cc_division": np.ones(n_cc, np.int32), "cc_division_name": "Unknown",
        "cc_company": np.ones(n_cc, np.int32), "cc_company_name": "Unknown",
        "cc_street_number": "501", "cc_street_name": "Main",
        "cc_street_type": "St", "cc_suite_number": "Suite 0",
        "cc_city": "Fairview", "cc_county": COUNTIES[0], "cc_state": "TN",
        "cc_zip": "35709", "cc_country": "United States",
        "cc_gmt_offset": -5.0, "cc_tax_percentage": 0.1})
    wsk = np.arange(1, n_web + 1)
    out["web_site"] = pd.DataFrame({
        "web_site_sk": wsk.astype(np.int64),
        "web_site_id": [f"AAAAAAAA{x:08d}" for x in wsk],
        "web_rec_start_date": "1998-01-01", "web_rec_end_date": None,
        "web_name": [f"site_{x % 4}" for x in wsk],
        "web_open_date_sk": DATE0_SK, "web_close_date_sk": None,
        "web_class": "Unknown", "web_manager": [f"Manager {x}" for x in wsk],
        "web_mkt_id": (wsk % 6 + 1).astype(np.int32),
        "web_mkt_class": "Unknown", "web_mkt_desc": "Unknown",
        "web_market_manager": "Unknown",
        "web_company_id": (wsk % 6 + 1).astype(np.int32),
        "web_company_name": np.array(["pri", "able", "ought", "ese", "anti",
                                      "cally"])[wsk % 6],
        "web_street_number": "501", "web_street_name": "Main",
        "web_street_type": "St", "web_suite_number": "Suite 0",
        "web_city": "Fairview", "web_county": COUNTIES[0], "web_state": "TN",
        "web_zip": "35709", "web_country": "United States",
        "web_gmt_offset": -5.0, "web_tax_percentage": 0.02})
    wp = np.arange(1, n_wp + 1)
    out["web_page"] = pd.DataFrame({
        "wp_web_page_sk": wp.astype(np.int64),
        "wp_web_page_id": [f"AAAAAAAA{x:08d}" for x in wp],
        "wp_rec_start_date": "1997-09-03", "wp_rec_end_date": None,
        "wp_creation_date_sk": DATE0_SK, "wp_access_date_sk": DATE0_SK,
        "wp_autogen_flag": np.array(["Y", "N"])[wp % 2],
        "wp_customer_sk": None,
        "wp_url": "http://www.foo.com", "wp_type": np.array(
            ["ad", "dynamic", "feedback", "general", "order",
             "protected", "welcome"])[wp % 7],
        "wp_char_count": (wp * 100).astype(np.int32),
        "wp_link_count": (wp % 25).astype(np.int32),
        "wp_image_count": (wp % 7).astype(np.int32),
        "wp_max_ad_count": (wp % 4).astype(np.int32)})
    cp = np.arange(1, n_cp + 1)
    out["catalog_page"] = pd.DataFrame({
        "cp_catalog_page_sk": cp.astype(np.int64),
        "cp_catalog_page_id": [f"AAAAAAAA{x:08d}" for x in cp],
        "cp_start_date_sk": DATE0_SK, "cp_end_date_sk": DATE0_SK + 100,
        "cp_department": "DEPARTMENT",
        "cp_catalog_number": (cp % 20 + 1).astype(np.int32),
        "cp_catalog_page_number": cp.astype(np.int32),
        "cp_description": [f"catalog page {x}" for x in cp],
        "cp_type": np.array(["bi-annual", "quarterly", "monthly"])[cp % 3]})

    # skewed fact marginals share one distribution set so cross-channel
    # identities (hot items are hot EVERYWHERE) hold like dsdgen's
    dists = None
    if skew is not None:
        dd = out["date_dim"]
        dists = SkewDists(
            rng, float(skew), n_items, n_cust, n_store, N_DAYS,
            out["item"]["i_category_id"].to_numpy(),
            dd["d_moy"].to_numpy(), dd["d_dow"].to_numpy())

    # ---- store_sales + store_returns -----------------------------------
    n_ss = sf_rows
    ss = _sales(rng, n_ss, "ss", N_DAYS, n_items, n_cust, n_addr, n_cdemo,
                n_hdemo, n_store, n_promo, dists=dists)
    out["store_sales"] = pd.DataFrame({
        "ss_sold_date_sk": ss["sold_date_sk"],
        "ss_sold_time_sk": ss["sold_time_sk"],
        "ss_item_sk": ss["item_sk"], "ss_customer_sk": ss["customer_sk"],
        "ss_cdemo_sk": ss["cdemo_sk"], "ss_hdemo_sk": ss["hdemo_sk"],
        "ss_addr_sk": ss["addr_sk"], "ss_store_sk": ss["store_sk"],
        "ss_promo_sk": ss["promo_sk"],
        "ss_ticket_number": ss["ticket_number"],
        "ss_quantity": ss["quantity"],
        "ss_wholesale_cost": ss["wholesale_cost"],
        "ss_list_price": ss["list_price"],
        "ss_sales_price": ss["sales_price"],
        "ss_ext_discount_amt": ss["ext_discount_amt"],
        "ss_ext_sales_price": ss["ext_sales_price"],
        "ss_ext_wholesale_cost": ss["ext_wholesale_cost"],
        "ss_ext_list_price": ss["ext_list_price"],
        "ss_ext_tax": ss["ext_tax"], "ss_coupon_amt": ss["coupon_amt"],
        "ss_net_paid": ss["net_paid"],
        "ss_net_paid_inc_tax": ss["net_paid_inc_tax"],
        "ss_net_profit": ss["net_profit"],
    })
    # returns reference ~25% of sales rows by (item, ticket, customer)
    # (raised from 10% so cross-channel return overlap — q83 — exists
    # at harness scale)
    ridx = rng.choice(n_ss, n_ss // 4, replace=False)
    ssr = out["store_sales"].iloc[ridx]
    n_sr = len(ssr)
    ret_qty = np.minimum(rng.integers(1, 101, n_sr),
                         ssr.ss_quantity.to_numpy())
    ret_amt = np.round(ssr.ss_sales_price.to_numpy() * ret_qty, 2)
    out["store_returns"] = pd.DataFrame({
        "sr_returned_date_sk": (np.array(
            [DATE0_SK if v is None else int(v)
             for v in ssr.ss_sold_date_sk.to_numpy()], np.int64)
            + rng.integers(1, 90, n_sr)),
        "sr_return_time_sk": rng.integers(0, 86400, n_sr).astype(np.int64),
        "sr_item_sk": ssr.ss_item_sk.to_numpy(),
        "sr_customer_sk": ssr.ss_customer_sk.to_numpy(),
        "sr_cdemo_sk": ssr.ss_cdemo_sk.to_numpy(),
        "sr_hdemo_sk": ssr.ss_hdemo_sk.to_numpy(),
        "sr_addr_sk": ssr.ss_addr_sk.to_numpy(),
        "sr_store_sk": ssr.ss_store_sk.to_numpy(),
        "sr_reason_sk": rng.integers(1, 36, n_sr).astype(np.int64),
        "sr_ticket_number": ssr.ss_ticket_number.to_numpy(),
        "sr_return_quantity": ret_qty.astype(np.int32),
        "sr_return_amt": ret_amt,
        "sr_return_tax": np.round(ret_amt * 0.08, 2),
        "sr_return_amt_inc_tax": np.round(ret_amt * 1.08, 2),
        "sr_fee": np.round(rng.uniform(0.5, 100.0, n_sr), 2),
        "sr_return_ship_cost": np.round(rng.uniform(0, 10, n_sr), 2),
        "sr_refunded_cash": np.round(ret_amt * 0.5, 2),
        "sr_reversed_charge": np.round(ret_amt * 0.3, 2),
        "sr_store_credit": np.round(ret_amt * 0.2, 2),
        "sr_net_loss": np.round(rng.uniform(0.5, 500.0, n_sr), 2),
    })

    # ---- catalog_sales + catalog_returns -------------------------------
    n_cs = sf_rows // 2
    cs = _sales(rng, n_cs, "cs", N_DAYS, n_items, n_cust, n_addr, n_cdemo,
                n_hdemo, n_store, n_promo, dists=dists)
    ship_cost = np.round(np.asarray(cs["ext_sales_price"]) * 0.05, 2)
    out["catalog_sales"] = pd.DataFrame({
        "cs_sold_date_sk": cs["sold_date_sk"],
        "cs_sold_time_sk": cs["sold_time_sk"],
        "cs_ship_date_sk": (np.where(
            pd.isna(cs["sold_date_sk"]), DATE0_SK,
            pd.array(cs["sold_date_sk"]).to_numpy(dtype=float,
                                                  na_value=DATE0_SK)
        ).astype(np.int64) + rng.integers(1, 120, n_cs)),
        "cs_bill_customer_sk": cs["customer_sk"],
        "cs_bill_cdemo_sk": cs["cdemo_sk"],
        "cs_bill_hdemo_sk": cs["hdemo_sk"],
        "cs_bill_addr_sk": cs["addr_sk"],
        "cs_ship_customer_sk": cs["customer_sk"],
        "cs_ship_cdemo_sk": cs["cdemo_sk"],
        "cs_ship_hdemo_sk": cs["hdemo_sk"],
        "cs_ship_addr_sk": cs["addr_sk"],
        "cs_call_center_sk": rng.integers(1, n_cc + 1, n_cs).astype(np.int64),
        "cs_catalog_page_sk": rng.integers(1, n_cp + 1,
                                           n_cs).astype(np.int64),
        "cs_ship_mode_sk": rng.integers(1, 21, n_cs).astype(np.int64),
        "cs_warehouse_sk": rng.integers(1, n_wh + 1, n_cs).astype(np.int64),
        "cs_item_sk": cs["item_sk"],
        "cs_promo_sk": cs["promo_sk"],
        "cs_order_number": np.arange(1, n_cs + 1, dtype=np.int64),
        "cs_quantity": cs["quantity"],
        "cs_wholesale_cost": cs["wholesale_cost"],
        "cs_list_price": cs["list_price"],
        "cs_sales_price": cs["sales_price"],
        "cs_ext_discount_amt": cs["ext_discount_amt"],
        "cs_ext_sales_price": cs["ext_sales_price"],
        "cs_ext_wholesale_cost": cs["ext_wholesale_cost"],
        "cs_ext_list_price": cs["ext_list_price"],
        "cs_ext_tax": cs["ext_tax"], "cs_coupon_amt": cs["coupon_amt"],
        "cs_ext_ship_cost": ship_cost,
        "cs_net_paid": cs["net_paid"],
        "cs_net_paid_inc_tax": cs["net_paid_inc_tax"],
        "cs_net_paid_inc_ship": np.round(
            np.asarray(cs["net_paid"]) + ship_cost, 2),
        "cs_net_paid_inc_ship_tax": np.round(
            np.asarray(cs["net_paid_inc_tax"]) + ship_cost, 2),
        "cs_net_profit": cs["net_profit"],
    })
    # link a third of catalog sales to store-return (customer, item) pairs —
    # the cross-channel join identity q17/q25/q29 aggregate over
    sr_t = out["store_returns"]
    n_link = min(n_cs // 3, 10 * len(sr_t))
    pick = rng.integers(0, len(sr_t), n_link)
    cs_t = out["catalog_sales"]
    cs_t.loc[:n_link - 1, "cs_bill_customer_sk"] = \
        sr_t.sr_customer_sk.to_numpy()[pick]
    cs_t.loc[:n_link - 1, "cs_item_sk"] = sr_t.sr_item_sk.to_numpy()[pick]
    cs_t.loc[:n_link - 1, "cs_sold_date_sk"] = \
        sr_t.sr_returned_date_sk.to_numpy()[pick] + rng.integers(0, 60, n_link)

    cidx = rng.choice(n_cs, n_cs // 4, replace=False)
    csr = out["catalog_sales"].iloc[cidx]
    n_cr = len(csr)
    cret_qty = np.minimum(rng.integers(1, 101, n_cr),
                          csr.cs_quantity.to_numpy())
    cret_amt = np.round(csr.cs_sales_price.to_numpy() * cret_qty, 2)
    out["catalog_returns"] = pd.DataFrame({
        "cr_returned_date_sk": (np.where(
            pd.isna(csr.cs_sold_date_sk), DATE0_SK,
            csr.cs_sold_date_sk.to_numpy(dtype=float, na_value=DATE0_SK)
        ).astype(np.int64) + rng.integers(1, 90, n_cr)),
        "cr_returned_time_sk": rng.integers(0, 86400, n_cr).astype(np.int64),
        "cr_item_sk": csr.cs_item_sk.to_numpy(),
        "cr_refunded_customer_sk": csr.cs_bill_customer_sk.to_numpy(),
        "cr_refunded_cdemo_sk": csr.cs_bill_cdemo_sk.to_numpy(),
        "cr_refunded_hdemo_sk": csr.cs_bill_hdemo_sk.to_numpy(),
        "cr_refunded_addr_sk": csr.cs_bill_addr_sk.to_numpy(),
        "cr_returning_customer_sk": csr.cs_bill_customer_sk.to_numpy(),
        "cr_returning_cdemo_sk": csr.cs_bill_cdemo_sk.to_numpy(),
        "cr_returning_hdemo_sk": csr.cs_bill_hdemo_sk.to_numpy(),
        "cr_returning_addr_sk": csr.cs_bill_addr_sk.to_numpy(),
        "cr_call_center_sk": csr.cs_call_center_sk.to_numpy(),
        "cr_catalog_page_sk": csr.cs_catalog_page_sk.to_numpy(),
        "cr_ship_mode_sk": csr.cs_ship_mode_sk.to_numpy(),
        "cr_warehouse_sk": csr.cs_warehouse_sk.to_numpy(),
        "cr_reason_sk": rng.integers(1, 36, n_cr).astype(np.int64),
        "cr_order_number": csr.cs_order_number.to_numpy(),
        "cr_return_quantity": cret_qty.astype(np.int32),
        "cr_return_amount": cret_amt,
        "cr_return_tax": np.round(cret_amt * 0.08, 2),
        "cr_return_amt_inc_tax": np.round(cret_amt * 1.08, 2),
        "cr_fee": np.round(rng.uniform(0.5, 100.0, n_cr), 2),
        "cr_return_ship_cost": np.round(rng.uniform(0, 10, n_cr), 2),
        "cr_refunded_cash": np.round(cret_amt * 0.5, 2),
        "cr_reversed_charge": np.round(cret_amt * 0.3, 2),
        "cr_store_credit": np.round(cret_amt * 0.2, 2),
        "cr_net_loss": np.round(rng.uniform(0.5, 500.0, n_cr), 2),
    })

    # ---- web_sales + web_returns ---------------------------------------
    n_ws = sf_rows // 4
    ws = _sales(rng, n_ws, "ws", N_DAYS, n_items, n_cust, n_addr, n_cdemo,
                n_hdemo, n_store, n_promo, dists=dists)
    wship_cost = np.round(np.asarray(ws["ext_sales_price"]) * 0.05, 2)
    out["web_sales"] = pd.DataFrame({
        "ws_sold_date_sk": ws["sold_date_sk"],
        "ws_sold_time_sk": ws["sold_time_sk"],
        "ws_ship_date_sk": (np.where(
            pd.isna(ws["sold_date_sk"]), DATE0_SK,
            pd.array(ws["sold_date_sk"]).to_numpy(dtype=float,
                                                  na_value=DATE0_SK)
        ).astype(np.int64) + rng.integers(1, 120, n_ws)),
        "ws_item_sk": ws["item_sk"],
        "ws_bill_customer_sk": ws["customer_sk"],
        "ws_bill_cdemo_sk": ws["cdemo_sk"],
        "ws_bill_hdemo_sk": ws["hdemo_sk"],
        "ws_bill_addr_sk": ws["addr_sk"],
        "ws_ship_customer_sk": ws["customer_sk"],
        "ws_ship_cdemo_sk": ws["cdemo_sk"],
        "ws_ship_hdemo_sk": ws["hdemo_sk"],
        "ws_ship_addr_sk": ws["addr_sk"],
        "ws_web_page_sk": rng.integers(1, n_wp + 1, n_ws).astype(np.int64),
        "ws_web_site_sk": rng.integers(1, n_web + 1, n_ws).astype(np.int64),
        "ws_ship_mode_sk": rng.integers(1, 21, n_ws).astype(np.int64),
        "ws_warehouse_sk": rng.integers(1, n_wh + 1, n_ws).astype(np.int64),
        "ws_promo_sk": ws["promo_sk"],
        "ws_order_number": np.arange(1, n_ws + 1, dtype=np.int64),
        "ws_quantity": ws["quantity"],
        "ws_wholesale_cost": ws["wholesale_cost"],
        "ws_list_price": ws["list_price"],
        "ws_sales_price": ws["sales_price"],
        "ws_ext_discount_amt": ws["ext_discount_amt"],
        "ws_ext_sales_price": ws["ext_sales_price"],
        "ws_ext_wholesale_cost": ws["ext_wholesale_cost"],
        "ws_ext_list_price": ws["ext_list_price"],
        "ws_ext_tax": ws["ext_tax"], "ws_coupon_amt": ws["coupon_amt"],
        "ws_ext_ship_cost": wship_cost,
        "ws_net_paid": ws["net_paid"],
        "ws_net_paid_inc_tax": ws["net_paid_inc_tax"],
        "ws_net_paid_inc_ship": np.round(
            np.asarray(ws["net_paid"]) + wship_cost, 2),
        "ws_net_paid_inc_ship_tax": np.round(
            np.asarray(ws["net_paid_inc_tax"]) + wship_cost, 2),
        "ws_net_profit": ws["net_profit"],
    })
    widx = rng.choice(n_ws, n_ws // 4, replace=False)
    wsr = out["web_sales"].iloc[widx]
    n_wr = len(wsr)
    wret_qty = np.minimum(rng.integers(1, 101, n_wr),
                          wsr.ws_quantity.to_numpy())
    wret_amt = np.round(wsr.ws_sales_price.to_numpy() * wret_qty, 2)
    out["web_returns"] = pd.DataFrame({
        "wr_returned_date_sk": (np.where(
            pd.isna(wsr.ws_sold_date_sk), DATE0_SK,
            wsr.ws_sold_date_sk.to_numpy(dtype=float, na_value=DATE0_SK)
        ).astype(np.int64) + rng.integers(1, 90, n_wr)),
        "wr_returned_time_sk": rng.integers(0, 86400, n_wr).astype(np.int64),
        "wr_item_sk": wsr.ws_item_sk.to_numpy(),
        "wr_refunded_customer_sk": wsr.ws_bill_customer_sk.to_numpy(),
        "wr_refunded_cdemo_sk": wsr.ws_bill_cdemo_sk.to_numpy(),
        "wr_refunded_hdemo_sk": wsr.ws_bill_hdemo_sk.to_numpy(),
        "wr_refunded_addr_sk": wsr.ws_bill_addr_sk.to_numpy(),
        "wr_returning_customer_sk": wsr.ws_bill_customer_sk.to_numpy(),
        "wr_returning_cdemo_sk": wsr.ws_bill_cdemo_sk.to_numpy(),
        "wr_returning_hdemo_sk": wsr.ws_bill_hdemo_sk.to_numpy(),
        "wr_returning_addr_sk": wsr.ws_bill_addr_sk.to_numpy(),
        "wr_web_page_sk": wsr.ws_web_page_sk.to_numpy(),
        "wr_reason_sk": rng.integers(1, 36, n_wr).astype(np.int64),
        "wr_order_number": wsr.ws_order_number.to_numpy(),
        "wr_return_quantity": wret_qty.astype(np.int32),
        "wr_return_amt": wret_amt,
        "wr_return_tax": np.round(wret_amt * 0.08, 2),
        "wr_return_amt_inc_tax": np.round(wret_amt * 1.08, 2),
        "wr_fee": np.round(rng.uniform(0.5, 100.0, n_wr), 2),
        "wr_return_ship_cost": np.round(rng.uniform(0, 10, n_wr), 2),
        "wr_refunded_cash": np.round(wret_amt * 0.5, 2),
        "wr_reversed_charge": np.round(wret_amt * 0.3, 2),
        "wr_account_credit": np.round(wret_amt * 0.2, 2),
        "wr_net_loss": np.round(rng.uniform(0.5, 500.0, n_wr), 2),
    })

    # ---- inventory ------------------------------------------------------
    inv_dates = DATE0_SK + np.arange(0, N_DAYS, 7)
    dsk, isk, wsk_ = np.meshgrid(inv_dates,
                                 np.arange(1, n_items + 1, 4),
                                 np.arange(1, n_wh + 1), indexing="ij")
    n_inv = dsk.size
    out["inventory"] = pd.DataFrame({
        "inv_date_sk": dsk.ravel().astype(np.int64),
        "inv_item_sk": isk.ravel().astype(np.int64),
        "inv_warehouse_sk": wsk_.ravel().astype(np.int64),
        "inv_quantity_on_hand": rng.integers(0, 1000,
                                             n_inv).astype(np.int32),
    })

    if measure_null_frac > 0.0:
        # NULL densities on the price/quantity measures (dsdgen leaves
        # sparse measures; aggregates must honor NULL-skipping at scale)
        measures = {
            "store_sales": ["ss_sales_price", "ss_ext_sales_price",
                            "ss_quantity", "ss_net_profit"],
            "catalog_sales": ["cs_quantity", "cs_sales_price"],
            "web_sales": ["ws_sales_price", "ws_quantity"],
        }
        for tname, cols in measures.items():
            pdf = out[tname]
            n = len(pdf)
            for c in cols:
                mask = rng.random(n) < measure_null_frac
                col = pdf[c].astype("float64").to_numpy(copy=True)
                col[mask] = np.nan
                pdf[c] = col

    # column order exactly per schema
    for name, cols in TABLES.items():
        df = out[name]
        out[name] = df[[c for c, _t in cols]]
    return out
