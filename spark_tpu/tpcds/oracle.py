"""Shared oracle-comparison helpers for the TPC-DS harnesses.

One source of truth for the sqlite dialect rewrite, value normalization,
and the fact-table list — used by the in-memory sweep, the file-backed
sweeps, the sharded smoke, and the mid-scale example (previously four
divergent copies)."""

from __future__ import annotations

import math
import re

import numpy as np

__all__ = ["FACT_TABLES", "sqlite_text", "norm_value", "row_key"]

FACT_TABLES = {"store_sales", "catalog_sales", "web_sales",
               "store_returns", "catalog_returns", "web_returns",
               "inventory"}


def sqlite_text(sql: str) -> str:
    """Adapt engine SQL to sqlite: expand STDDEV_SAMP via moments."""
    return re.sub(
        r"STDDEV_SAMP\((\w+)\)",
        r"(CASE WHEN count(\1) > 1 THEN "
        r"sqrt(max(sum(\1*\1*1.0) - count(\1)*avg(\1)*avg(\1), 0)"
        r" / (count(\1) - 1)) ELSE NULL END)",
        sql, flags=re.IGNORECASE)


def norm_value(v):
    """Engine/sqlite value → comparable canonical form."""
    if v is None:
        return None
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return None if math.isnan(f) else round(f, 6)
    return str(v)


def row_key(row):
    """NULL-stable sort key for order-insensitive row comparison."""
    return tuple("\0" if x is None else str(x) for x in row)
