"""Expression IR with dual-path evaluation.

The analog of Catalyst's expression tree
(``sql/catalyst/.../expressions/Expression.scala``), redesigned for XLA:

* every expression evaluates VECTORIZED over a whole ColumnBatch — there is
  no row-at-a-time path at all;
* ``eval(ctx)`` is written against an array-module ``ctx.xp`` that is either
  numpy (interpreted/host path) or jax.numpy (traced path).  Running the same
  code under ``jax.jit`` IS the codegen path — XLA plays Janino
  (``codegen/CodeGenerator.scala:905``) — and the numpy run is the
  interpreted oracle, preserving the reference's dual-path testing pattern
  (``ExpressionEvalHelper`` cross-checks eval vs codegen);
* NULLs are validity masks threaded through every operator, with Kleene
  three-valued logic for AND/OR (reference ``expressions/predicates.scala``);
* string expressions are DICTIONARY transforms: the host rewrites the (small)
  sorted dictionary and the device only gathers/remaps int32 codes.  This is
  the TPU replacement for ``UTF8String.java`` byte-twiddling.

Aggregate functions live in ``spark_tpu.aggregates``.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from . import types as T
from .columnar import ColumnBatch

__all__ = [
    "ExprValue", "EvalContext", "Expression", "Col", "Literal", "Alias",
    "Cast", "Add", "Sub", "Mul", "Div", "IntDiv", "Mod", "Pow", "Neg",
    "UnaryMath", "RoundExpr", "EQ", "NE", "LT", "LE", "GT", "GE", "EqNullSafe",
    "And", "Or", "Not", "IsNull", "IsNotNull", "IsNaN", "Coalesce", "If",
    "CaseWhen", "In", "Between", "StringPredicate", "StringTransform",
    "StringLength", "Concat", "Substring", "ExtractDatePart", "Hash64",
    "Greatest", "Least", "RowIndex", "Rand", "lit", "col", "AnalysisException",
    "TimeWindow", "parse_duration",
]


class AnalysisException(Exception):
    """Resolution/type error (reference ``sql/AnalysisException.scala``)."""


class ExprValue(NamedTuple):
    """A vectorized value: data array (+ scalar broadcastable), optional
    validity mask (None = no NULLs), optional string dictionary."""

    data: Any
    valid: Optional[Any]
    dictionary: Optional[Tuple] = None


def and_valid(xp, a: Optional[Any], b: Optional[Any]) -> Optional[Any]:
    if a is None:
        return b
    if b is None:
        return a
    return a & b


class EvalContext:
    """Evaluation environment: a ColumnBatch plus the array module.

    ``xp`` is numpy for the interpreted path, jax.numpy inside jit traces.
    ``row_offset`` decorrelates RowIndex/Rand across operators/partitions
    (the upper-bits analog of MonotonicallyIncreasingID's partition id).
    """

    def __init__(self, batch: ColumnBatch, xp, row_offset: int = 0):
        self.batch = batch
        self.xp = xp
        self.capacity = batch.capacity
        self.row_offset = row_offset

    def col(self, name: str) -> ExprValue:
        vec = self.batch.column(name)
        return ExprValue(vec.data, vec.valid, vec.dictionary)

    def broadcast(self, value: ExprValue) -> ExprValue:
        """Materialize scalars to full capacity (project output)."""
        data = value.data
        if getattr(data, "shape", ()) == ():
            data = self.xp.broadcast_to(data, (self.capacity,))
        elif not hasattr(data, "shape"):
            data = self.xp.full((self.capacity,), data)
        valid = value.valid
        if valid is not None and getattr(valid, "shape", ()) == ():
            valid = self.xp.broadcast_to(valid, (self.capacity,))
        return ExprValue(data, valid, value.dictionary)


class Expression:
    """Base expression node: typed, vectorized, rewritable."""

    children: Tuple["Expression", ...] = ()

    # -- analysis ---------------------------------------------------------
    def data_type(self, schema: T.StructType) -> T.DataType:
        raise NotImplementedError

    def references(self) -> set:
        out = set()
        for c in self.children:
            out |= c.references()
        return out

    @property
    def foldable(self) -> bool:
        return bool(self.children) and all(c.foldable for c in self.children)

    def map_children(self, fn: Callable[["Expression"], "Expression"]) -> "Expression":
        """Rebuild this node with transformed children (rule rewrites)."""
        if not self.children:
            return self
        import copy
        new = copy.copy(self)
        new.children = tuple(fn(c) for c in self.children)
        return new

    def transform_up(self, fn) -> "Expression":
        node = self.map_children(lambda c: c.transform_up(fn))
        return fn(node)

    # -- execution --------------------------------------------------------
    def eval(self, ctx: EvalContext) -> ExprValue:
        raise NotImplementedError

    # -- display ----------------------------------------------------------
    @property
    def name(self) -> str:
        """Auto-generated output column name (Catalyst ``toString``)."""
        return repr(self)

    def __repr__(self) -> str:  # pragma: no cover
        args = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__.lower()}({args})"

    # -- sugar (the user-facing Column API builds on these) ---------------
    def __add__(self, o): return Add(self, _wrap(o))
    def __radd__(self, o): return Add(_wrap(o), self)
    def __sub__(self, o): return Sub(self, _wrap(o))
    def __rsub__(self, o): return Sub(_wrap(o), self)
    def __mul__(self, o): return Mul(self, _wrap(o))
    def __rmul__(self, o): return Mul(_wrap(o), self)
    def __truediv__(self, o): return Div(self, _wrap(o))
    def __rtruediv__(self, o): return Div(_wrap(o), self)
    def __mod__(self, o): return Mod(self, _wrap(o))
    def __neg__(self): return Neg(self)
    def __eq__(self, o): return EQ(self, _wrap(o))  # type: ignore[override]
    def __ne__(self, o): return NE(self, _wrap(o))  # type: ignore[override]
    def __lt__(self, o): return LT(self, _wrap(o))
    def __le__(self, o): return LE(self, _wrap(o))
    def __gt__(self, o): return GT(self, _wrap(o))
    def __ge__(self, o): return GE(self, _wrap(o))
    def __and__(self, o): return And(self, _wrap(o))
    def __or__(self, o): return Or(self, _wrap(o))
    def __invert__(self): return Not(self)
    def __hash__(self):  # __eq__ is overloaded; identity hash keeps sets working
        return id(self)


def _wrap(v: Any) -> Expression:
    return v if isinstance(v, Expression) else Literal(v)


def lit(v: Any) -> Expression:
    return _wrap(v)


def col(name: str) -> "Col":
    return Col(name)


# ---------------------------------------------------------------------------
# Leaves
# ---------------------------------------------------------------------------

class Col(Expression):
    """Column reference (``AttributeReference`` after resolution)."""

    def __init__(self, name: str):
        self._name = name

    @property
    def name(self) -> str:
        return self._name

    @property
    def foldable(self) -> bool:
        return False

    def data_type(self, schema: T.StructType) -> T.DataType:
        try:
            return schema[self._name].dataType
        except KeyError:
            raise AnalysisException(
                f"cannot resolve column '{self._name}' among ({', '.join(schema.names)})")

    def references(self) -> set:
        return {self._name}

    def eval(self, ctx: EvalContext) -> ExprValue:
        return ctx.col(self._name)

    def __repr__(self) -> str:
        return self._name


class _SlotBindings(threading.local):
    """Per-thread Literal→parameter bindings for the serving plan cache.

    Parameterized plan sharing (serving/plancache.py) traces ONE jit
    program per plan SHAPE and feeds literal values in as runtime scalar
    arguments.  The binding is thread-local and keyed by Literal object
    identity — never object mutation — so a concurrent execution of a
    plan that happens to share Literal objects (optimizer rules reuse
    untouched subtrees) can never observe another thread's tracers."""

    map: Optional[dict] = None


_slot_bindings = _SlotBindings()


class Literal(Expression):
    def __init__(self, value: Any, dtype: Optional[T.DataType] = None):
        self.value = value
        self.dtype = dtype or T.infer_type(value)

    @property
    def foldable(self) -> bool:
        return True

    def data_type(self, schema: T.StructType) -> T.DataType:
        return self.dtype

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        bindings = _slot_bindings.map
        if bindings is not None:
            bound = bindings.get(id(self))
            if bound is not None:
                # slotted parameter: the VALUE arrives as a traced scalar
                # argument of the cached executable, not a baked constant
                return ExprValue(xp.asarray(bound), None)
        if self.value is None:
            return ExprValue(xp.zeros((), self.dtype.np_dtype),
                             xp.zeros((), bool))
        if self.dtype.is_string:
            # a lone string literal: single-entry dictionary, code 0
            return ExprValue(xp.zeros((), np.int32), None, (str(self.value),))
        if isinstance(self.dtype, T.DecimalType):
            scaled = int(round(float(self.value) * 10 ** self.dtype.scale))
            return ExprValue(xp.asarray(scaled, dtype=np.int64), None)
        if isinstance(self.dtype, T.DateType):
            return ExprValue(xp.asarray(np.datetime64(self.value, "D").astype(np.int32)), None)
        if isinstance(self.dtype, T.TimestampType):
            return ExprValue(xp.asarray(np.datetime64(self.value, "us").astype(np.int64)), None)
        return ExprValue(xp.asarray(self.value, dtype=self.dtype.np_dtype), None)

    def __repr__(self) -> str:
        return repr(self.value)


class Alias(Expression):
    def __init__(self, child: Expression, alias: str):
        self.children = (child,)
        self._alias = alias

    @property
    def name(self) -> str:
        return self._alias

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval(self, ctx):
        return self.children[0].eval(ctx)

    def __repr__(self) -> str:
        return f"{self.children[0]!r} AS {self._alias}"


# ---------------------------------------------------------------------------
# Arithmetic (reference expressions/arithmetic.scala)
# ---------------------------------------------------------------------------

class BinaryArithmetic(Expression):
    op_name = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def data_type(self, schema):
        lt_, rt = (c.data_type(schema) for c in self.children)
        if isinstance(lt_, T.NullType):
            return rt
        if isinstance(rt, T.NullType):
            return lt_
        return T.numeric_promote(lt_, rt)

    def _compute(self, xp, a, b):
        raise NotImplementedError

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        dt = self.data_type(ctx.batch.schema)
        a = l.data.astype(dt.np_dtype)
        b = r.data.astype(dt.np_dtype)
        return ExprValue(self._compute(xp, a, b), and_valid(xp, l.valid, r.valid))

    def __repr__(self) -> str:
        return f"({self.children[0]!r} {self.op_name} {self.children[1]!r})"


class Add(BinaryArithmetic):
    op_name = "+"
    def _compute(self, xp, a, b): return a + b


class Sub(BinaryArithmetic):
    op_name = "-"
    def _compute(self, xp, a, b): return a - b


class Mul(BinaryArithmetic):
    op_name = "*"
    def _compute(self, xp, a, b): return a * b


class Div(BinaryArithmetic):
    """True division; x/0 → NULL (ANSI-off Spark semantics)."""

    op_name = "/"

    def data_type(self, schema):
        dt = super().data_type(schema)
        return dt if dt.is_fractional else T.float64

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        dt = self.data_type(ctx.batch.schema)
        zero = r.data == 0
        a = l.data.astype(dt.np_dtype)
        b = xp.where(zero, xp.ones((), r.data.dtype), r.data).astype(dt.np_dtype)
        valid = and_valid(xp, and_valid(xp, l.valid, r.valid), ~zero)
        return ExprValue(a / b, valid)


class IntDiv(Div):
    op_name = "div"

    def data_type(self, schema):
        return T.int64

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        zero = r.data == 0
        b = xp.where(zero, xp.ones((), r.data.dtype), r.data)
        valid = and_valid(xp, and_valid(xp, l.valid, r.valid), ~zero)
        return ExprValue((l.data // b).astype(np.int64), valid)


class Mod(BinaryArithmetic):
    op_name = "%"

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        dt = self.data_type(ctx.batch.schema)
        zero = r.data == 0
        a = l.data.astype(dt.np_dtype)
        b = xp.where(zero, xp.ones((), r.data.dtype), r.data).astype(dt.np_dtype)
        valid = and_valid(xp, and_valid(xp, l.valid, r.valid), ~zero)
        # Spark % keeps the sign of the dividend (Java semantics), i.e. fmod —
        # not numpy's floored mod.
        if dt.is_fractional:
            res = xp.fmod(a, b)
        else:
            res = (xp.sign(a) * (xp.abs(a) % xp.abs(b))).astype(dt.np_dtype)
        return ExprValue(res, valid)


class Pow(BinaryArithmetic):
    op_name = "pow"

    def data_type(self, schema):
        return T.float64

    def _compute(self, xp, a, b):
        return xp.power(a, b)


class Neg(Expression):
    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        return ExprValue(-v.data, v.valid)

    def __repr__(self):
        return f"(- {self.children[0]!r})"


class UnaryMath(Expression):
    """sqrt/exp/log/sin/... — float64 elementwise fns (mathExpressions.scala).

    Domain errors (log of ≤0, sqrt of <0) produce NULL like Spark's NaN→null
    behavior is emulated by masking.
    """

    FNS = {
        "sqrt": (lambda xp, x: xp.sqrt(xp.maximum(x, 0.0)), lambda xp, x: x >= 0),
        "exp": (lambda xp, x: xp.exp(x), None),
        "ln": (lambda xp, x: xp.log(xp.where(x > 0, x, 1.0)), lambda xp, x: x > 0),
        "log10": (lambda xp, x: xp.log10(xp.where(x > 0, x, 1.0)), lambda xp, x: x > 0),
        "log2": (lambda xp, x: xp.log2(xp.where(x > 0, x, 1.0)), lambda xp, x: x > 0),
        "sin": (lambda xp, x: xp.sin(x), None),
        "cos": (lambda xp, x: xp.cos(x), None),
        "tan": (lambda xp, x: xp.tan(x), None),
        "asin": (lambda xp, x: xp.arcsin(xp.clip(x, -1, 1)), lambda xp, x: xp.abs(x) <= 1),
        "acos": (lambda xp, x: xp.arccos(xp.clip(x, -1, 1)), lambda xp, x: xp.abs(x) <= 1),
        "atan": (lambda xp, x: xp.arctan(x), None),
        "sinh": (lambda xp, x: xp.sinh(x), None),
        "cosh": (lambda xp, x: xp.cosh(x), None),
        "tanh": (lambda xp, x: xp.tanh(x), None),
        "floor": (lambda xp, x: xp.floor(x), None),
        "ceil": (lambda xp, x: xp.ceil(x), None),
        "abs": (lambda xp, x: xp.abs(x), None),
        "sign": (lambda xp, x: xp.sign(x), None),
        "radians": (lambda xp, x: x * (math.pi / 180.0), None),
        "degrees": (lambda xp, x: x * (180.0 / math.pi), None),
        "log1p": (lambda xp, x: xp.log1p(xp.where(x > -1, x, 0.0)),
                  lambda xp, x: x > -1),
        "expm1": (lambda xp, x: xp.expm1(x), None),
        "cbrt": (lambda xp, x: xp.cbrt(x), None),
        "rint": (lambda xp, x: xp.round(x), None),
    }

    def __init__(self, fn: str, child: Expression):
        assert fn in self.FNS, fn
        self.fn = fn
        self.children = (child,)

    def data_type(self, schema):
        if self.fn in ("floor", "ceil"):
            return T.int64
        if self.fn in ("abs", "sign"):
            return self.children[0].data_type(schema)
        return T.float64

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        if self.fn in ("abs", "sign"):
            return ExprValue(xp.abs(v.data) if self.fn == "abs" else xp.sign(v.data), v.valid)
        x = v.data.astype(np.float64)
        fn, domain = self.FNS[self.fn]
        out = fn(xp, x)
        valid = v.valid
        if domain is not None:
            valid = and_valid(xp, valid, domain(xp, x))
        if self.fn in ("floor", "ceil"):
            out = out.astype(np.int64)
        return ExprValue(out, valid)

    def __repr__(self):
        return f"{self.fn}({self.children[0]!r})"


class RoundExpr(Expression):
    def __init__(self, child: Expression, scale: int = 0):
        self.children = (child,)
        self.scale = scale

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        return dt if dt.is_numeric else T.float64

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        if not np.issubdtype(np.asarray(v.data).dtype if ctx.xp is np else v.data.dtype, np.floating):
            return v
        factor = 10.0 ** self.scale
        # HALF_UP like Spark, not banker's rounding
        out = xp.floor(xp.abs(v.data) * factor + 0.5) / factor * xp.sign(v.data)
        return ExprValue(out, v.valid)

    def __repr__(self):
        return f"round({self.children[0]!r}, {self.scale})"


# ---------------------------------------------------------------------------
# Comparisons & boolean logic (reference expressions/predicates.scala)
# ---------------------------------------------------------------------------

def _comparison_operands(ctx: EvalContext, le: Expression, re_: Expression):
    """Evaluate both sides coerced to a common comparable representation.

    Strings compare by dictionary code, which is order-correct only when both
    sides share a dictionary; a string literal vs a column is rewritten into
    code space via searchsorted on the host dictionary (static under jit).
    """
    xp = ctx.xp
    l, r = le.eval(ctx), re_.eval(ctx)
    if l.dictionary is not None or r.dictionary is not None:
        if l.dictionary is not None and r.dictionary is not None:
            if l.dictionary == r.dictionary:
                return l, r, True
            if len(r.dictionary) == 1:  # literal side
                word = r.dictionary[0]
                idx = int(np.searchsorted(np.array(l.dictionary, dtype=object), word))
                exact = idx < len(l.dictionary) and l.dictionary[idx] == word
                # map literal into left's code space: for exact match use the
                # code; otherwise use idx-0.5 boundary → encode by doubling
                return (ExprValue(l.data * 2, l.valid, None),
                        ExprValue(xp.asarray(idx * 2 if exact else idx * 2 - 1, np.int64),
                                  r.valid, None), True)
            if len(l.dictionary) == 1:
                word = l.dictionary[0]
                idx = int(np.searchsorted(np.array(r.dictionary, dtype=object), word))
                exact = idx < len(r.dictionary) and r.dictionary[idx] == word
                return (ExprValue(xp.asarray(idx * 2 if exact else idx * 2 - 1, np.int64),
                                  l.valid, None),
                        ExprValue(r.data * 2, r.valid, None), True)
            # two dictionary-coded columns: dictionaries are trace-time
            # static, so align by merging them and remapping both code
            # spaces (the remap tables bake into the program as constants)
            from .columnar import merge_dictionaries
            _merged, ra, rb = merge_dictionaries(l.dictionary, r.dictionary)
            ldata, rdata = l.data, r.data
            if len(ra):
                ldata = xp.asarray(ra)[xp.clip(ldata, 0, len(ra) - 1)]
            if len(rb):
                rdata = xp.asarray(rb)[xp.clip(rdata, 0, len(rb) - 1)]
            return (ExprValue(ldata, l.valid, None),
                    ExprValue(rdata, r.valid, None), True)
        raise AnalysisException("cannot compare string with non-string")
    return l, r, False


class BinaryComparison(Expression):
    op_name = "?"

    def __init__(self, left: Expression, right: Expression):
        self.children = (left, right)

    def data_type(self, schema):
        lt_, rt = (c.data_type(schema) for c in self.children)
        if T.common_type(lt_, rt) is None and not (lt_ == rt):
            raise AnalysisException(f"cannot compare {lt_} and {rt}")
        return T.boolean

    def _compute(self, xp, a, b):
        raise NotImplementedError

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r, is_str = _comparison_operands(ctx, *self.children)
        if not is_str:
            ct = T.common_type(self.children[0].data_type(ctx.batch.schema),
                               self.children[1].data_type(ctx.batch.schema))
            np_dt = (ct or T.float64).np_dtype
            a, b = l.data.astype(np_dt), r.data.astype(np_dt)
        else:
            a, b = l.data, r.data
        return ExprValue(self._compute(xp, a, b), and_valid(xp, l.valid, r.valid))

    def __repr__(self):
        return f"({self.children[0]!r} {self.op_name} {self.children[1]!r})"


class EQ(BinaryComparison):
    op_name = "="
    def _compute(self, xp, a, b): return a == b


class NE(BinaryComparison):
    op_name = "!="
    def _compute(self, xp, a, b): return a != b


class LT(BinaryComparison):
    op_name = "<"
    def _compute(self, xp, a, b): return a < b


class LE(BinaryComparison):
    op_name = "<="
    def _compute(self, xp, a, b): return a <= b


class GT(BinaryComparison):
    op_name = ">"
    def _compute(self, xp, a, b): return a > b


class GE(BinaryComparison):
    op_name = ">="
    def _compute(self, xp, a, b): return a >= b


class EqNullSafe(BinaryComparison):
    """<=> : NULL-safe equality, never NULL itself."""

    op_name = "<=>"

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        l, r, _ = _comparison_operands(ctx, *self.children)
        lv = l.valid if l.valid is not None else xp.ones((), bool)
        rv = r.valid if r.valid is not None else xp.ones((), bool)
        eq = (l.data == r.data) & lv & rv
        both_null = ~lv & ~rv
        return ExprValue(eq | both_null, None)


class And(Expression):
    """Kleene AND: F & NULL = F, T & NULL = NULL."""

    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        lv = l.valid if l.valid is not None else xp.ones((), bool)
        rv = r.valid if r.valid is not None else xp.ones((), bool)
        data = (l.data | ~lv) & (r.data | ~rv)  # null treated true, then masked
        valid = (lv & rv) | (lv & ~l.data) | (rv & ~r.data)
        if l.valid is None and r.valid is None:
            valid = None
        return ExprValue(data & (valid if valid is not None else True), valid)

    def __repr__(self):
        return f"({self.children[0]!r} AND {self.children[1]!r})"


class Or(Expression):
    """Kleene OR: T | NULL = T, F | NULL = NULL."""

    def __init__(self, left, right):
        self.children = (left, right)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        l, r = (c.eval(ctx) for c in self.children)
        lv = l.valid if l.valid is not None else xp.ones((), bool)
        rv = r.valid if r.valid is not None else xp.ones((), bool)
        data = (l.data & lv) | (r.data & rv)
        valid = (lv & rv) | (lv & l.data) | (rv & r.data)
        if l.valid is None and r.valid is None:
            valid = None
        return ExprValue(data, valid)

    def __repr__(self):
        return f"({self.children[0]!r} OR {self.children[1]!r})"


class Not(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        return ExprValue(~v.data, v.valid)

    def __repr__(self):
        return f"(NOT {self.children[0]!r})"


# ---------------------------------------------------------------------------
# Null handling & conditionals (nullExpressions.scala, conditionalExpressions.scala)
# ---------------------------------------------------------------------------

class IsNull(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        if v.valid is None:
            return ExprValue(xp.zeros((), bool), None)
        return ExprValue(~v.valid, None)

    def __repr__(self):
        return f"({self.children[0]!r} IS NULL)"


class IsNotNull(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        if v.valid is None:
            return ExprValue(xp.ones((), bool), None)
        return ExprValue(v.valid, None)

    def __repr__(self):
        return f"({self.children[0]!r} IS NOT NULL)"


class IsNaN(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        d = v.data
        if not np.issubdtype(np.dtype(str(d.dtype)), np.floating):
            return ExprValue(xp.zeros((), bool), None)
        return ExprValue(xp.isnan(d), None)


def _align_value_dicts(xp, vals):
    """Re-encode ExprValues that carry different string dictionaries onto one
    merged dictionary (host-merged, device-gathered; static under jit).
    Returns (vals, merged_dictionary_or_None)."""
    dicts = [v.dictionary for v in vals if v.dictionary is not None]
    if not dicts:
        return vals, None
    if all(d == dicts[0] for d in dicts):
        return vals, dicts[0]
    merged = tuple(sorted(set().union(*[set(d) for d in dicts])))
    lookup = {w: i for i, w in enumerate(merged)}
    out = []
    for v in vals:
        if v.dictionary is None:
            out.append(v)
            continue
        remap = xp.asarray(
            np.fromiter((lookup[w] for w in v.dictionary), np.int32,
                        count=len(v.dictionary)))
        out.append(ExprValue(remap[xp.clip(v.data, 0, None)], v.valid, merged))
    return out, merged


class Coalesce(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self, schema):
        out = T.null_type
        for c in self.children:
            nxt = T.common_type(out, c.data_type(schema))
            if nxt is None:
                raise AnalysisException("incompatible coalesce branches")
            out = nxt
        return out

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.data_type(ctx.batch.schema)
        vals = [c.eval(ctx) for c in self.children]
        vals, merged = _align_value_dicts(xp, vals)
        dicts = [merged] if merged is not None else []
        out = ExprValue(vals[-1].data.astype(dt.np_dtype), vals[-1].valid,
                        dicts[0] if dicts else None)
        for v in reversed(vals[:-1]):
            if v.valid is None:
                out = ExprValue(v.data.astype(dt.np_dtype), None, out.dictionary)
            else:
                taken_valid = out.valid if out.valid is not None else xp.ones((), bool)
                out = ExprValue(
                    xp.where(v.valid, v.data.astype(dt.np_dtype), out.data),
                    v.valid | taken_valid, out.dictionary)
        return out

    def __repr__(self):
        return f"coalesce({', '.join(map(repr, self.children))})"


class If(Expression):
    def __init__(self, pred, then, otherwise):
        self.children = (pred, then, otherwise)

    def data_type(self, schema):
        t = T.common_type(self.children[1].data_type(schema),
                          self.children[2].data_type(schema))
        if t is None:
            raise AnalysisException("IF branches have incompatible types")
        return t

    def eval(self, ctx):
        xp = ctx.xp
        p, a, b = (c.eval(ctx) for c in self.children)
        dt = self.data_type(ctx.batch.schema)
        (a, b), merged = _align_value_dicts(xp, [a, b])
        dicts = [merged] if merged is not None else []
        cond = p.data & (p.valid if p.valid is not None else True)
        data = xp.where(cond, a.data.astype(dt.np_dtype), b.data.astype(dt.np_dtype))
        av = a.valid if a.valid is not None else xp.ones((), bool)
        bv = b.valid if b.valid is not None else xp.ones((), bool)
        valid = None if (a.valid is None and b.valid is None) else xp.where(cond, av, bv)
        return ExprValue(data, valid, dicts[0] if dicts else None)

    def __repr__(self):
        p, a, b = self.children
        return f"if({p!r}, {a!r}, {b!r})"


class CaseWhen(Expression):
    """CASE WHEN p1 THEN v1 ... ELSE d END — desugars to nested If at eval."""

    def __init__(self, branches: Sequence[Tuple[Expression, Expression]],
                 otherwise: Optional[Expression] = None):
        self.branches = [(p, v) for p, v in branches]
        self.otherwise = otherwise if otherwise is not None else Literal(None)
        flat: List[Expression] = []
        for p, v in self.branches:
            flat += [p, v]
        flat.append(self.otherwise)
        self.children = tuple(flat)

    def map_children(self, fn):
        new_branches = [(fn(p), fn(v)) for p, v in self.branches]
        return CaseWhen(new_branches, fn(self.otherwise))

    def _as_if(self) -> Expression:
        node: Expression = self.otherwise
        for p, v in reversed(self.branches):
            node = If(p, v, node)
        return node

    def data_type(self, schema):
        return self._as_if().data_type(schema)

    def eval(self, ctx):
        return self._as_if().eval(ctx)

    def __repr__(self):
        parts = " ".join(f"WHEN {p!r} THEN {v!r}" for p, v in self.branches)
        return f"CASE {parts} ELSE {self.otherwise!r} END"


class In(Expression):
    """`x IN (lit, lit, ...)` — ORs of equality, vectorized as isin."""

    def __init__(self, child: Expression, values: Sequence[Any]):
        self.children = (child,)
        self.values = [v.value if isinstance(v, Literal) else v for v in values]

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        if v.dictionary is not None:
            member = np.array([w in set(self.values) for w in v.dictionary], bool)
            member = xp.asarray(member)
            data = xp.where(v.data >= 0, member[xp.clip(v.data, 0, None)], False)
            return ExprValue(data, v.valid)
        acc = xp.zeros((), bool)
        for val in self.values:
            acc = acc | (v.data == val)
        return ExprValue(acc, v.valid)

    def __repr__(self):
        return f"({self.children[0]!r} IN {tuple(self.values)!r})"


class Between(Expression):
    def __repr__(self):
        c = self.children
        return f"({c[0]!r} BETWEEN {c[1]!r} AND {c[2]!r})"

    def __init__(self, child, low, high):
        self.children = (child, _wrap(low), _wrap(high))

    def data_type(self, schema):
        return T.boolean

    def eval(self, ctx):
        c, lo, hi = self.children
        return And(GE(c, lo), LE(c, hi)).eval(ctx)


class Greatest(Expression):
    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self, schema):
        out = self.children[0].data_type(schema)
        for c in self.children[1:]:
            out = T.numeric_promote(out, c.data_type(schema))
        return out

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.data_type(ctx.batch.schema)
        vals = [c.eval(ctx) for c in self.children]
        out = vals[0].data.astype(dt.np_dtype)
        valid = vals[0].valid
        for v in vals[1:]:
            out = xp.maximum(out, v.data.astype(dt.np_dtype))
            valid = and_valid(xp, valid, v.valid)
        return ExprValue(out, valid)


class Least(Greatest):
    def eval(self, ctx):
        xp = ctx.xp
        dt = self.data_type(ctx.batch.schema)
        vals = [c.eval(ctx) for c in self.children]
        out = vals[0].data.astype(dt.np_dtype)
        valid = vals[0].valid
        for v in vals[1:]:
            out = xp.minimum(out, v.data.astype(dt.np_dtype))
            valid = and_valid(xp, valid, v.valid)
        return ExprValue(out, valid)


# ---------------------------------------------------------------------------
# Cast (reference expressions/Cast.scala)
# ---------------------------------------------------------------------------

class Cast(Expression):
    def __init__(self, child: Expression, to: T.DataType):
        self.children = (child,)
        self.to = to

    def data_type(self, schema):
        return self.to

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        src = self.children[0].data_type(ctx.batch.schema)
        to = self.to
        if src == to:
            return v
        if v.dictionary is not None:
            # string → X: parse the dictionary on host, gather on device
            if to.is_string:
                return v
            def parse(fn, default):
                arr = []
                ok = []
                for w in v.dictionary:
                    try:
                        arr.append(fn(w)); ok.append(True)
                    except (ValueError, TypeError):
                        arr.append(default); ok.append(False)
                return (xp.asarray(np.array(arr, to.np_dtype)),
                        xp.asarray(np.array(ok, bool)))
            if to.is_numeric:
                if isinstance(to, T.DecimalType):
                    table, ok = parse(lambda w: int(round(float(w) * 10 ** to.scale)), 0)
                else:
                    table, ok = parse(float if to.is_fractional else (lambda w: int(float(w))), 0)
            elif isinstance(to, T.DateType):
                table, ok = parse(lambda w: np.datetime64(w, "D").astype(np.int32), 0)
            elif isinstance(to, T.TimestampType):
                table, ok = parse(lambda w: np.datetime64(w, "us").astype(np.int64), 0)
            elif isinstance(to, T.BooleanType):
                table, ok = parse(lambda w: w.strip().lower() in ("true", "t", "1", "yes", "y"), False)
            else:
                raise AnalysisException(f"unsupported cast string→{to}")
            codes = xp.clip(v.data, 0, None)
            return ExprValue(table[codes], and_valid(xp, v.valid, ok[codes]))
        if to.is_string:
            raise AnalysisException(
                "cast to string requires host materialization (non-jittable); "
                "wrap in a HostCast at planning time")
        if isinstance(src, T.DecimalType):
            f = v.data.astype(np.float64) / (10 ** src.scale)
            if isinstance(to, T.DecimalType):
                return ExprValue(xp.round(f * 10 ** to.scale).astype(np.int64), v.valid)
            return ExprValue(f.astype(to.np_dtype), v.valid)
        if isinstance(to, T.DecimalType):
            return ExprValue(xp.round(v.data.astype(np.float64) * 10 ** to.scale).astype(np.int64), v.valid)
        if isinstance(src, T.DateType) and isinstance(to, T.TimestampType):
            return ExprValue(v.data.astype(np.int64) * 86_400_000_000, v.valid)
        if isinstance(src, T.TimestampType) and isinstance(to, T.DateType):
            return ExprValue(xp.floor_divide(v.data, 86_400_000_000).astype(np.int32), v.valid)
        if isinstance(to, T.BooleanType):
            return ExprValue(v.data != 0, v.valid)
        # float → integral needs JVM-exact semantics on BOTH lanes
        # ((long)f: truncate toward zero, saturate at long bounds, NaN→0;
        # then mod-wrap into the narrow type) — numpy's direct astype of
        # out-of-range floats is platform UB and diverges from XLA
        if np.issubdtype(np.dtype(getattr(v.data, "dtype", np.float64)),
                         np.floating) and to.is_integral:
            f = v.data.astype(np.float64)
            t = xp.trunc(xp.where(xp.isnan(f), 0.0, f))
            if np.dtype(to.np_dtype).itemsize >= 8:
                # largest float64 strictly below 2^63 — clipping to
                # float(2^63-1) would round UP to 2^63 and wrap
                lo, hi = float(np.iinfo(np.int64).min), \
                    float(np.nextafter(2.0 ** 63, 0.0))
                sat = np.int64(np.iinfo(np.int64).max)
            else:
                # JVM narrows through int: saturate at int32, then the
                # astype below mod-wraps into short/byte exactly like
                # (short)(int)f / (byte)(int)f
                lo, hi = float(np.iinfo(np.int32).min), \
                    float(np.iinfo(np.int32).max)
                sat = np.int64(np.iinfo(np.int32).max)
            out = xp.clip(t, lo, hi).astype(np.int64)
            # ONLY above-range values saturate: hi itself (e.g. the
            # exactly-representable nextafter(2^63) for int64) converts
            # exactly via astype, matching JVM (long)f
            out = xp.where(t > hi, sat, out)
            return ExprValue(out.astype(to.np_dtype), v.valid)
        # numeric/bool → numeric: plain astype (truncating float→int like Spark)
        return ExprValue(v.data.astype(to.np_dtype), v.valid)

    def __repr__(self):
        return f"CAST({self.children[0]!r} AS {self.to!r})"


# ---------------------------------------------------------------------------
# String expressions — dictionary transforms (stringExpressions.scala)
# ---------------------------------------------------------------------------

def _dict_gather(xp, table: np.ndarray, codes, valid):
    t = xp.asarray(table)
    return t[xp.clip(codes, 0, None)]


class StringTransform(Expression):
    """upper/lower/trim/reverse/...: host rewrites the dictionary, device
    remaps codes.  The output dictionary is re-sorted so downstream
    comparisons stay order-correct."""

    FNS = {
        "upper": str.upper,
        "lower": str.lower,
        "trim": str.strip,
        "ltrim": str.lstrip,
        "rtrim": str.rstrip,
        "reverse": lambda s: s[::-1],
        "initcap": lambda s: s.title(),
    }

    def __init__(self, fn: str, child: Expression):
        assert fn in self.FNS
        self.fn = fn
        self.children = (child,)

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not ct.is_string:
            raise AnalysisException(f"{self.fn} expects string, got {ct}")
        return T.string

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        return _rewrite_dictionary(ctx.xp, v, self.FNS[self.fn])

    def __repr__(self):
        return f"{self.fn}({self.children[0]!r})"


def _rewrite_dictionary(xp, v: ExprValue, fn) -> ExprValue:
    """Shared host-rewrites-dictionary/device-remaps-codes contract for
    every string→string transform (StringTransform + the parameterized
    family)."""
    transformed = [fn(w) for w in (v.dictionary or ())]
    new_dict = tuple(sorted(set(transformed))) or ("",)
    pos = {w: i for i, w in enumerate(new_dict)}
    remap = np.array([pos[w] for w in transformed], np.int32) \
        if transformed else np.zeros(1, np.int32)
    return ExprValue(_dict_gather(xp, remap, v.data, v.valid), v.valid,
                     new_dict)


class Substring(Expression):
    """substring(s, pos, len) with static pos/len (1-based, Spark semantics)."""

    def __init__(self, child: Expression, pos: int, length: int):
        self.children = (child,)
        self.pos = pos
        self.length = length

    def data_type(self, schema):
        return T.string

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        start = self.pos - 1 if self.pos > 0 else self.pos
        transformed = []
        for w in v.dictionary:
            s = w[start:] if start >= 0 else w[len(w) + start:]
            transformed.append(s[:self.length])
        new_dict = tuple(sorted(set(transformed)))
        pos = {w: i for i, w in enumerate(new_dict)}
        remap = np.array([pos[w] for w in transformed], np.int32) if transformed else np.zeros(1, np.int32)
        return ExprValue(_dict_gather(xp, remap, v.data, v.valid), v.valid, new_dict)

    def __repr__(self):
        return f"substring({self.children[0]!r}, {self.pos}, {self.length})"


class StringLength(Expression):
    def __init__(self, child):
        self.children = (child,)

    def data_type(self, schema):
        return T.int32

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        lens = np.array([len(w) for w in v.dictionary], np.int32) if v.dictionary else np.zeros(1, np.int32)
        return ExprValue(_dict_gather(xp, lens, v.data, v.valid), v.valid)

    def __repr__(self):
        return f"length({self.children[0]!r})"


class StringPredicate(Expression):
    """LIKE / startswith / endswith / contains / rlike: host evaluates the
    predicate over the dictionary, device gathers a boolean."""

    def __init__(self, kind: str, child: Expression, pattern: str):
        assert kind in ("like", "startswith", "endswith", "contains", "rlike")
        self.kind = kind
        self.children = (child,)
        self.pattern = pattern

    def data_type(self, schema):
        return T.boolean

    def _matcher(self) -> Callable[[str], bool]:
        import re as _re
        if self.kind == "like":
            # translate SQL LIKE to regex (% → .*, _ → .)
            out = []
            i = 0
            p = self.pattern
            while i < len(p):
                ch = p[i]
                if ch == "\\" and i + 1 < len(p):
                    out.append(_re.escape(p[i + 1])); i += 2; continue
                if ch == "%":
                    out.append(".*")
                elif ch == "_":
                    out.append(".")
                else:
                    out.append(_re.escape(ch))
                i += 1
            rx = _re.compile("^" + "".join(out) + "$", _re.DOTALL)
            return lambda s: rx.match(s) is not None
        if self.kind == "rlike":
            rx = _re.compile(self.pattern)
            return lambda s: rx.search(s) is not None
        if self.kind == "startswith":
            return lambda s: s.startswith(self.pattern)
        if self.kind == "endswith":
            return lambda s: s.endswith(self.pattern)
        return lambda s: self.pattern in s

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        m = self._matcher()
        table = np.array([m(w) for w in v.dictionary], bool) if v.dictionary else np.zeros(1, bool)
        return ExprValue(_dict_gather(xp, table, v.data, v.valid), v.valid)

    def __repr__(self):
        return f"({self.children[0]!r} {self.kind} {self.pattern!r})"


class Concat(Expression):
    """concat of string columns/literals.

    The output dictionary is the cross product of input dictionaries — fine
    for low-cardinality columns, rejected above a size limit (the honest
    dynamic-shape boundary; high-cardinality concat belongs on the host).
    """

    MAX_DICT = 1 << 20

    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self, schema):
        return T.string

    def eval(self, ctx):
        xp = ctx.xp
        vals = [c.eval(ctx) for c in self.children]
        dicts = [v.dictionary if v.dictionary is not None else ("",) for v in vals]
        size = 1
        for d in dicts:
            size *= max(len(d), 1)
        if size > self.MAX_DICT:
            raise AnalysisException(
                f"concat dictionary blowup ({size}); use host path")
        # pairwise fold: combine two dictionary-coded values at a time
        cur = vals[0]
        cur_dict = dicts[0]
        for v, d in zip(vals[1:], dicts[1:]):
            combined = [a + b for a in cur_dict for b in d]
            new_dict = tuple(sorted(set(combined)))
            pos = {w: i for i, w in enumerate(new_dict)}
            remap = np.array([[pos[a + b] for b in d] for a in cur_dict], np.int32)
            remap = remap if remap.size else np.zeros((1, 1), np.int32)
            table = xp.asarray(remap)
            code = table[xp.clip(cur.data, 0, None), xp.clip(v.data, 0, None)]
            cur = ExprValue(code, and_valid(xp, cur.valid, v.valid), new_dict)
            cur_dict = new_dict
        return cur

    def __repr__(self):
        return f"concat({', '.join(map(repr, self.children))})"


# ---------------------------------------------------------------------------
# Datetime extraction (datetimeExpressions.scala)
# ---------------------------------------------------------------------------

def parse_duration(text) -> int:
    """'10 seconds' / '5 minutes' / '1 hour' / '2 days' -> microseconds.

    The CalendarInterval subset event-time windows and watermarks need
    (reference `unsafe/types/CalendarInterval.java` parsing, fixed-length
    units only — months/years are not fixed durations)."""
    if isinstance(text, (int, float)):
        return int(text)
    parts = str(text).strip().lower().split()
    if len(parts) != 2:
        raise AnalysisException(
            f"cannot parse duration {text!r}: expected '<n> <unit>'")
    try:
        n = float(parts[0])
    except ValueError:
        raise AnalysisException(f"cannot parse duration {text!r}")
    unit = parts[1].rstrip("s")
    scale = {"microsecond": 1, "millisecond": 1_000, "second": 1_000_000,
             "minute": 60_000_000, "hour": 3_600_000_000,
             "day": 86_400_000_000, "week": 7 * 86_400_000_000}.get(unit)
    if scale is None:
        raise AnalysisException(f"unknown duration unit {parts[1]!r}")
    return int(n * scale)


class TimeWindow(Expression):
    """Tumbling event-time bucket (`expressions/TimeWindow.scala`):
    start = floor(ts / duration) * duration; `field` picks start or end.

    Nested struct output (Spark's window.start/.end) is flattened into the
    field choice — sliding windows (slide < duration) need row expansion
    (Expand) and are not supported yet."""

    def __init__(self, child: Expression, duration_us: int,
                 slide_us: Optional[int] = None, field: str = "start"):
        if int(duration_us) <= 0:
            raise AnalysisException(
                f"window duration must be positive, got {duration_us}us")
        slide = int(slide_us) if slide_us is not None else int(duration_us)
        if slide <= 0 or int(duration_us) % slide != 0:
            raise AnalysisException(
                "window slide must be positive and divide the duration "
                f"evenly; got duration={duration_us}us slide={slide}us")
        if int(duration_us) // slide > 512:
            # each event expands into duration/slide rows (static shapes);
            # an unbounded ratio would explode analysis and batch capacity
            raise AnalysisException(
                f"window duration/slide ratio {duration_us // slide} "
                "exceeds the supported maximum of 512 windows per event")
        assert field in ("start", "end"), field
        self.duration_us = int(duration_us)
        self.slide_us = slide
        self.field = field
        self.children = (child,)

    @property
    def is_sliding(self) -> bool:
        return self.slide_us != self.duration_us

    def map_children(self, fn):
        return TimeWindow(fn(self.children[0]), self.duration_us,
                          self.slide_us, self.field)

    @property
    def name(self):
        return "window" if self.field == "start" else "window_end"

    def data_type(self, schema):
        src = self.children[0].data_type(schema)
        if not (isinstance(src, T.TimestampType) or src.is_integral):
            raise AnalysisException(
                f"window() needs a timestamp/integral column, got {src}")
        return T.timestamp

    def eval(self, ctx):
        if self.is_sliding:
            raise AnalysisException(
                "sliding window() must be a grouping key (the analyzer "
                "expands events into their windows); it cannot be "
                "evaluated as a plain expression")
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        d = np.int64(self.duration_us)
        start = xp.floor_divide(v.data.astype(np.int64), d) * d
        out = start if self.field == "start" else start + d
        return ExprValue(out, v.valid)

    def __repr__(self):
        return (f"window({self.children[0]!r}, {self.duration_us}us"
                + (f", slide={self.slide_us}us" if self.is_sliding else "")
                + f").{self.field}")


class ExtractDatePart(Expression):
    """year/month/day/... from date (days) or timestamp (micros) columns,
    via Hinnant's civil-from-days integer algorithm — pure elementwise int
    ops, so it fuses into the surrounding XLA program."""

    PARTS = ("year", "month", "day", "dayofweek", "dayofyear", "quarter",
             "hour", "minute", "second", "weekofyear")

    def __init__(self, part: str, child: Expression):
        assert part in self.PARTS, part
        self.part = part
        self.children = (child,)

    def data_type(self, schema):
        return T.int32

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        src = self.children[0].data_type(ctx.batch.schema)
        if isinstance(src, T.TimestampType):
            days = xp.floor_divide(v.data, 86_400_000_000)
            micros_in_day = v.data - days * 86_400_000_000
        elif isinstance(src, T.DateType):
            days = v.data.astype(np.int64)
            micros_in_day = xp.zeros((), np.int64)
        else:
            raise AnalysisException(f"cannot extract {self.part} from {src}")

        if self.part == "hour":
            return ExprValue((micros_in_day // 3_600_000_000).astype(np.int32), v.valid)
        if self.part == "minute":
            return ExprValue(((micros_in_day // 60_000_000) % 60).astype(np.int32), v.valid)
        if self.part == "second":
            return ExprValue(((micros_in_day // 1_000_000) % 60).astype(np.int32), v.valid)
        if self.part == "dayofweek":
            # Spark: 1 = Sunday. 1970-01-01 was a Thursday.
            return ExprValue(((days + 4) % 7 + 1).astype(np.int32), v.valid)

        # civil_from_days (Howard Hinnant, public domain algorithm)
        z = days + 719_468
        era = xp.floor_divide(z, 146_097)
        doe = z - era * 146_097
        yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
        y = yoe + era * 400
        doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
        mp = (5 * doy + 2) // 153
        d = doy - (153 * mp + 2) // 5 + 1
        m = xp.where(mp < 10, mp + 3, mp - 9)
        y = xp.where(m <= 2, y + 1, y)
        if self.part == "year":
            return ExprValue(y.astype(np.int32), v.valid)
        if self.part == "month":
            return ExprValue(m.astype(np.int32), v.valid)
        if self.part == "day":
            return ExprValue(d.astype(np.int32), v.valid)
        if self.part == "quarter":
            return ExprValue(((m - 1) // 3 + 1).astype(np.int32), v.valid)
        if self.part == "dayofyear":
            jan1 = _days_from_civil(xp, y, 1, 1)
            return ExprValue((days - jan1 + 1).astype(np.int32), v.valid)
        if self.part == "weekofyear":
            # ISO week number
            dow = (days + 3) % 7  # 0 = Monday
            thursday = days - dow + 3
            z2 = thursday + 719_468
            era2 = xp.floor_divide(z2, 146_097)
            doe2 = z2 - era2 * 146_097
            yoe2 = (doe2 - doe2 // 1460 + doe2 // 36_524 - doe2 // 146_096) // 365
            iso_year = yoe2 + era2 * 400
            doy2 = doe2 - (365 * yoe2 + yoe2 // 4 - yoe2 // 100)
            mp2 = (5 * doy2 + 2) // 153
            m2 = xp.where(mp2 < 10, mp2 + 3, mp2 - 9)
            iso_year = xp.where(m2 <= 2, iso_year + 1, iso_year)
            jan4 = _days_from_civil(xp, iso_year, 1, 4)
            week1_mon = jan4 - (jan4 + 3) % 7
            return ExprValue(((days - week1_mon) // 7 + 1).astype(np.int32), v.valid)
        raise AssertionError(self.part)

    def __repr__(self):
        return f"{self.part}({self.children[0]!r})"


def _days_from_civil(xp, y, m: int, d: int):
    """Inverse of civil_from_days for an array of years y and static month/day."""
    y = y - (1 if m <= 2 else 0)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = (m + 9) % 12
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


# ---------------------------------------------------------------------------
# Hashing — bit-exact across hosts/devices for shuffle partitioning
# ---------------------------------------------------------------------------

class Hash64(Expression):
    """Deterministic 64-bit mix hash (splitmix64 finalizer) of one or more
    columns.  The role of ``Murmur3_x86_32`` (reference
    ``unsafe/hash/Murmur3_x86_32.java``): agreement between partitioners on
    every host/device, here guaranteed by identical integer ops in XLA/numpy.
    NULL hashes to a fixed constant; string columns hash their dictionary
    WORDS (host-side stable hash of the bytes), not codes, so the value is
    independent of the batch dictionary."""

    NULL_HASH = np.int64(0x9E3779B97F4A7C15 - (1 << 64))

    def __init__(self, *children):
        self.children = tuple(children)

    def data_type(self, schema):
        return T.int64

    @staticmethod
    def _mix(xp, x):
        # murmur3/splitmix finalizer in uint64 (wraparound, logical shifts)
        c1 = np.uint64(0xFF51AFD7ED558CCD)
        c2 = np.uint64(0xC4CEB9FE1A85EC53)
        x = xp.asarray(x).astype(np.uint64)
        x = x ^ (x >> np.uint64(33))
        x = x * c1
        x = x ^ (x >> np.uint64(33))
        x = x * c2
        x = x ^ (x >> np.uint64(33))
        return x.astype(np.int64)

    @staticmethod
    def _string_hash_table(dictionary: Tuple[str, ...]) -> np.ndarray:
        import hashlib
        out = np.empty(max(len(dictionary), 1), np.int64)
        out[:] = 0
        for i, w in enumerate(dictionary):
            data = w if isinstance(w, bytes) else str(w).encode("utf-8")
            h = hashlib.blake2b(data, digest_size=8).digest()
            out[i] = np.frombuffer(h, np.int64)[0]
        return out

    def eval(self, ctx):
        xp = ctx.xp
        acc = xp.asarray(np.int64(42))
        for c in self.children:
            v = c.eval(ctx)
            if v.dictionary is not None:
                # clip BOTH ends: NULL (-1) codes and out-of-dictionary
                # sentinels (e.g. a remap's INT32_MAX) must gather in
                # bounds; both are masked/never-match downstream
                table = xp.asarray(self._string_hash_table(v.dictionary))
                h = table[xp.clip(v.data, 0, max(len(v.dictionary) - 1, 0))]
            else:
                bits = v.data
                if np.issubdtype(np.dtype(str(bits.dtype)), np.floating):
                    # normalize -0.0 → 0.0 then bitcast
                    bits = xp.where(bits == 0, xp.zeros((), bits.dtype), bits)
                    bits = bits.astype(np.float64).view(np.int64) if xp is np \
                        else _jax_bitcast(bits)
                h = self._mix(xp, bits.astype(np.int64))
            if v.valid is not None:
                h = xp.where(v.valid, h, self.NULL_HASH)
            combined = (xp.asarray(acc).astype(np.uint64) * np.uint64(31)
                        + xp.asarray(h).astype(np.uint64))
            acc = self._mix(xp, combined)
        return ExprValue(acc, None)

    def __repr__(self):
        return f"hash64({', '.join(map(repr, self.children))})"


def _jax_bitcast(x):
    import jax
    import jax.numpy as jnp
    return jax.lax.bitcast_convert_type(x.astype(jnp.float64), jnp.int64)


class RowIndex(Expression):
    """Global row id: batch-local index + the context's partition offset
    (``monotonically_increasing_id`` analog — reference
    ``expressions/MonotonicallyIncreasingID.scala`` packs partition id in the
    upper bits; here the offset is provided by the executing operator)."""

    def data_type(self, schema):
        return T.int64

    @property
    def foldable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        offset = getattr(ctx, "row_offset", 0)
        return ExprValue(xp.arange(ctx.capacity, dtype=np.int64) + offset, None)

    def __repr__(self):
        return "monotonically_increasing_id()"


class Rand(Expression):
    """Deterministic per-row uniform [0,1): counter-based (hash of row index
    and seed), so it is reproducible and identical between the interpreted
    and compiled paths — unlike Spark's stateful XORShiftRandom
    (``expressions/randomExpressions.scala``), which is seeded per-partition.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed

    def data_type(self, schema):
        return T.float64

    @property
    def foldable(self) -> bool:
        return False

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        offset = getattr(ctx, "row_offset", 0)
        idx = xp.arange(ctx.capacity, dtype=np.int64) + offset
        seed_mix = np.uint64((self.seed * 2654435761 + 1) & 0xFFFFFFFFFFFFFFFF)
        mixed = Hash64._mix(xp, (idx.astype(np.uint64)
                                 * np.uint64(0x9E3779B97F4A7C15)
                                 + seed_mix))
        u = (mixed.astype(np.uint64) >> np.uint64(11)).astype(np.float64)
        return ExprValue(u * (1.0 / (1 << 53)), None)

    def __repr__(self):
        return f"rand({self.seed})"


# ---------------------------------------------------------------------------
# Expression breadth: parameterized string transforms, date arithmetic,
# binary math (the long tail of `stringExpressions.scala`,
# `datetimeExpressions.scala`, `mathExpressions.scala`)
# ---------------------------------------------------------------------------

def _civil_ymd_vec(xp, days):
    """(y, m, d) int arrays from day numbers (civil_from_days, vectorized)."""
    z = days + 719_468
    era = xp.floor_divide(z, 146_097)
    doe = z - era * 146_097
    yoe = (doe - doe // 1460 + doe // 36_524 - doe // 146_096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = xp.where(mp < 10, mp + 3, mp - 9)
    y = xp.where(m <= 2, y + 1, y)
    return y, m, d


def _days_from_civil_vec(xp, y, m, d):
    """day numbers from (y, m, d) int arrays (days_from_civil, vectorized)."""
    y = xp.where(m <= 2, y - 1, y)
    era = xp.floor_divide(y, 400)
    yoe = y - era * 400
    mp = xp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146_097 + doe - 719_468


def _month_len_vec(xp, y, m):
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    # Jan..Dec lengths, Feb patched by leapness
    table = xp.asarray(np.array([31, 28, 31, 30, 31, 30, 31, 31, 30, 31,
                                 30, 31], np.int64))
    base = table[xp.clip(m - 1, 0, 11)]
    return xp.where((m == 2) & leap, 29, base)


def _as_days(xp, v: ExprValue, dt) -> Any:
    if isinstance(dt, T.TimestampType):
        return xp.floor_divide(v.data, 86_400_000_000).astype(np.int64)
    if isinstance(dt, T.DateType) or dt.is_integral:
        return v.data.astype(np.int64)
    raise AnalysisException(f"expected a date/timestamp, got {dt}")


class DateArith(Expression):
    """date_add/date_sub/datediff/add_months/months_between/last_day —
    pure elementwise integer calendar math (Hinnant algorithms), so every
    date function fuses into the surrounding XLA program instead of
    round-tripping through host datetime objects."""

    KINDS = ("date_add", "date_sub", "datediff", "add_months",
             "months_between", "last_day")

    def __init__(self, kind: str, *children: Expression):
        assert kind in self.KINDS, kind
        self.kind = kind
        self.children = tuple(children)

    def map_children(self, fn):
        return DateArith(self.kind, *[fn(c) for c in self.children])

    def data_type(self, schema):
        if self.kind == "datediff":
            return T.int32
        if self.kind == "months_between":
            return T.float64
        return T.date

    def eval(self, ctx):
        xp = ctx.xp
        schema = ctx.batch.schema
        a = ctx.broadcast(self.children[0].eval(ctx))
        da = _as_days(xp, a, self.children[0].data_type(schema))
        if self.kind == "last_day":
            y, m, _d = _civil_ymd_vec(xp, da)
            out = _days_from_civil_vec(xp, y, m, _month_len_vec(xp, y, m))
            return ExprValue(out.astype(np.int32), a.valid)
        b = ctx.broadcast(self.children[1].eval(ctx))
        valid = and_valid(xp, a.valid, b.valid)
        if self.kind in ("date_add", "date_sub"):
            n = b.data.astype(np.int64)
            out = da + (n if self.kind == "date_add" else -n)
            return ExprValue(out.astype(np.int32), valid)
        if self.kind == "datediff":
            db = _as_days(xp, b, self.children[1].data_type(schema))
            return ExprValue((da - db).astype(np.int32), valid)
        if self.kind == "add_months":
            y, m, d = _civil_ymd_vec(xp, da)
            total = (y * 12 + (m - 1)) + b.data.astype(np.int64)
            ny = xp.floor_divide(total, 12)
            nm = total - ny * 12 + 1
            nd = xp.minimum(d, _month_len_vec(xp, ny, nm))
            out = _days_from_civil_vec(xp, ny, nm, nd)
            return ExprValue(out.astype(np.int32), valid)
        # months_between (Spark's rule: integer when same day-of-month or
        # both month ends; else day difference / 31, rounded to 8 digits)
        db = _as_days(xp, b, self.children[1].data_type(schema))
        y1, m1, d1 = _civil_ymd_vec(xp, da)
        y2, m2, d2 = _civil_ymd_vec(xp, db)
        whole = ((y1 - y2) * 12 + (m1 - m2)).astype(np.float64)
        last1 = d1 == _month_len_vec(xp, y1, m1)
        last2 = d2 == _month_len_vec(xp, y2, m2)
        frac = (d1 - d2).astype(np.float64) / 31.0
        out = xp.where((d1 == d2) | (last1 & last2), whole, whole + frac)
        return ExprValue(xp.round(out * 1e8) / 1e8, valid)

    def __repr__(self):
        return f"{self.kind}({', '.join(map(repr, self.children))})"


class NextDay(Expression):
    """next_day(date, 'Mon'): the first date later than `date` falling on
    the given weekday (datetimeExpressions.scala NextDay)."""

    DOW = {"sun": 0, "mon": 1, "tue": 2, "wed": 3, "thu": 4, "fri": 5,
           "sat": 6}

    def __init__(self, child: Expression, day_name: str):
        key = str(day_name).strip().lower()[:3]
        if key not in self.DOW:
            raise AnalysisException(f"unknown weekday {day_name!r}")
        self.day_name = key
        self.children = (child,)

    def map_children(self, fn):
        return NextDay(fn(self.children[0]), self.day_name)

    def data_type(self, schema):
        return T.date

    def eval(self, ctx):
        xp = ctx.xp
        v = ctx.broadcast(self.children[0].eval(ctx))
        days = _as_days(xp, v, self.children[0].data_type(ctx.batch.schema))
        # 1970-01-01 was Thursday; dow 0 = Sunday
        cur = (days + 4) % 7
        target = np.int64(self.DOW[self.day_name])
        delta = (target - cur + 7) % 7
        delta = xp.where(delta == 0, 7, delta)
        return ExprValue((days + delta).astype(np.int32), v.valid)

    def __repr__(self):
        return f"next_day({self.children[0]!r}, {self.day_name!r})"


class TruncDate(Expression):
    """trunc(date, 'year'|'month'|'week'|'quarter') -> date."""

    def __init__(self, child: Expression, fmt: str):
        key = str(fmt).strip().lower()
        aliases = {"yy": "year", "yyyy": "year", "mm": "month",
                   "mon": "month"}
        key = aliases.get(key, key)
        if key not in ("year", "month", "week", "quarter"):
            raise AnalysisException(f"unknown trunc unit {fmt!r}")
        self.fmt = key
        self.children = (child,)

    def map_children(self, fn):
        return TruncDate(fn(self.children[0]), self.fmt)

    def data_type(self, schema):
        return T.date

    def eval(self, ctx):
        xp = ctx.xp
        v = ctx.broadcast(self.children[0].eval(ctx))
        days = _as_days(xp, v, self.children[0].data_type(ctx.batch.schema))
        if self.fmt == "week":      # Monday start
            out = days - (days + 3) % 7
        else:
            y, m, _d = _civil_ymd_vec(xp, days)
            if self.fmt == "year":
                m = xp.ones_like(m)
            elif self.fmt == "quarter":
                m = ((m - 1) // 3) * 3 + 1
            out = _days_from_civil_vec(xp, y, m, xp.ones_like(days))
        return ExprValue(out.astype(np.int32), v.valid)

    def __repr__(self):
        return f"trunc({self.children[0]!r}, {self.fmt!r})"


class UnixTimestamp(Expression):
    """unix_timestamp(ts) -> seconds since epoch (int64); from_unixtime
    (`FromUnixTime`) is the inverse returning a TIMESTAMP (deviation: the
    reference formats to string; string materialization is host-side)."""

    def __init__(self, child: Expression, inverse: bool = False):
        self.inverse = inverse
        self.children = (child,)

    def map_children(self, fn):
        return UnixTimestamp(fn(self.children[0]), self.inverse)

    def data_type(self, schema):
        return T.timestamp if self.inverse else T.int64

    def eval(self, ctx):
        xp = ctx.xp
        v = ctx.broadcast(self.children[0].eval(ctx))
        dt = self.children[0].data_type(ctx.batch.schema)
        if self.inverse:
            return ExprValue(v.data.astype(np.int64) * 1_000_000, v.valid)
        if isinstance(dt, T.DateType):
            return ExprValue(v.data.astype(np.int64) * 86_400, v.valid)
        return ExprValue(xp.floor_divide(v.data.astype(np.int64),
                                         1_000_000), v.valid)

    def __repr__(self):
        op = "from_unixtime" if self.inverse else "unix_timestamp"
        return f"{op}({self.children[0]!r})"


class BinaryMath(Expression):
    """hypot/atan2/nanvl — float64 elementwise binaries."""

    FNS = {
        "hypot": lambda xp, a, b: xp.hypot(a, b),
        "atan2": lambda xp, a, b: xp.arctan2(a, b),
        "nanvl": lambda xp, a, b: xp.where(xp.isnan(a), b, a),
    }

    def __init__(self, fn: str, left: Expression, right: Expression):
        assert fn in self.FNS, fn
        self.fn = fn
        self.children = (left, right)

    def map_children(self, fn):
        return BinaryMath(self.fn, fn(self.children[0]), fn(self.children[1]))

    def data_type(self, schema):
        return T.float64

    def eval(self, ctx):
        xp = ctx.xp
        a = ctx.broadcast(self.children[0].eval(ctx))
        b = ctx.broadcast(self.children[1].eval(ctx))
        out = self.FNS[self.fn](xp, a.data.astype(np.float64),
                                b.data.astype(np.float64))
        return ExprValue(out, and_valid(xp, a.valid, b.valid))

    def __repr__(self):
        return f"{self.fn}({self.children[0]!r}, {self.children[1]!r})"


def _soundex(word: str) -> str:
    codes = {"b": "1", "f": "1", "p": "1", "v": "1",
             "c": "2", "g": "2", "j": "2", "k": "2", "q": "2", "s": "2",
             "x": "2", "z": "2", "d": "3", "t": "3", "l": "4",
             "m": "5", "n": "5", "r": "6"}
    w = "".join(c for c in word.upper() if c.isalpha())
    if not w:
        return word
    out = [w[0]]
    prev = codes.get(w[0].lower(), "")
    for c in w[1:]:
        code = codes.get(c.lower(), "")
        if code and code != prev:
            out.append(code)
        if c.lower() not in ("h", "w"):
            prev = code
    return (out[0] + "".join(out[1:]) + "000")[:4]


class ParamStringTransform(Expression):
    """String→string transforms with STATIC parameters (regexp_replace,
    lpad, translate, md5, ...): the host rewrites the dictionary once per
    trace, the device only remaps int32 codes — same contract as
    StringTransform."""

    @staticmethod
    def _make(kind, params):
        import base64 as b64
        import hashlib
        import re as re_mod
        if kind == "regexp_replace":
            pat, repl = params
            rx = re_mod.compile(pat)
            return lambda s: rx.sub(repl, s)
        if kind == "regexp_extract":
            pat, idx = params
            rx = re_mod.compile(pat)

            def ex(s):
                m = rx.search(s)
                return m.group(idx) if m else ""
            return ex
        if kind == "lpad":
            n, pad = params
            return lambda s: s.rjust(n, pad)[:n] if pad else s[:n]
        if kind == "rpad":
            n, pad = params
            return lambda s: s.ljust(n, pad)[:n] if pad else s[:n]
        if kind == "translate":
            frm, to = params
            table = str.maketrans(frm[:len(to)], to[:len(frm)],
                                  frm[len(to):])
            return lambda s: s.translate(table)
        if kind == "repeat":
            (n,) = params
            return lambda s: s * n
        if kind == "soundex":
            return _soundex
        if kind == "md5":
            return lambda s: hashlib.md5(s.encode()).hexdigest()
        if kind == "sha1":
            return lambda s: hashlib.sha1(s.encode()).hexdigest()
        if kind == "sha2":
            (bits,) = params
            return lambda s: hashlib.new(f"sha{bits}",
                                         s.encode()).hexdigest()
        if kind == "base64":
            return lambda s: b64.b64encode(s.encode()).decode()
        if kind == "unbase64":
            return lambda s: b64.b64decode(s.encode()).decode("utf-8",
                                                              "replace")
        if kind == "hex":
            return lambda s: s.encode().hex().upper()
        raise AnalysisException(f"unknown string transform {kind}")

    def __init__(self, kind: str, child: Expression, params: tuple = ()):
        self.kind = kind
        self.params = tuple(params)
        self._fn = self._make(kind, self.params)
        self.children = (child,)

    def map_children(self, fn):
        return ParamStringTransform(self.kind, fn(self.children[0]),
                                    self.params)

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not ct.is_string:
            raise AnalysisException(f"{self.kind} expects string, got {ct}")
        return T.string

    def eval(self, ctx):
        v = self.children[0].eval(ctx)
        return _rewrite_dictionary(ctx.xp, v, self._fn)

    def __repr__(self):
        return f"{self.kind}({self.children[0]!r}, {self.params})"


class StringToInt(Expression):
    """String→int64 via a host-computed dictionary table (instr/locate/
    levenshtein-vs-literal/crc32)."""

    @staticmethod
    def _make(kind, params):
        import zlib
        if kind == "instr":
            (sub,) = params
            return lambda s: s.find(sub) + 1
        if kind == "locate":
            sub, start = params
            return lambda s: s.find(sub, max(start - 1, 0)) + 1
        if kind == "levenshtein":
            (other,) = params

            def lev(s):
                a, b = s, other
                if len(a) < len(b):
                    a, b = b, a
                prev = list(range(len(b) + 1))
                for i, ca in enumerate(a, 1):
                    cur = [i]
                    for j, cb in enumerate(b, 1):
                        cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                                       prev[j - 1] + (ca != cb)))
                    prev = cur
                return prev[-1]
            return lev
        if kind == "crc32":
            return lambda s: zlib.crc32(s.encode()) & 0xFFFFFFFF
        raise AnalysisException(f"unknown string→int transform {kind}")

    def __init__(self, kind: str, child: Expression, params: tuple = ()):
        self.kind = kind
        self.params = tuple(params)
        self._fn = self._make(kind, self.params)
        self.children = (child,)

    def map_children(self, fn):
        return StringToInt(self.kind, fn(self.children[0]), self.params)

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not ct.is_string:
            raise AnalysisException(f"{self.kind} expects string, got {ct}")
        return T.int64

    def eval(self, ctx):
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        table = np.array([self._fn(w) for w in v.dictionary] or [0],
                         np.int64)
        codes = xp.clip(v.data, 0, None)
        return ExprValue(xp.asarray(table)[codes], v.valid)

    def __repr__(self):
        return f"{self.kind}({self.children[0]!r}, {self.params})"


class Randn(Rand):
    """Standard-normal draws (randn): Box-Muller over two Rand streams —
    deterministic per (seed, row index) like Rand."""

    def eval(self, ctx: EvalContext) -> ExprValue:
        xp = ctx.xp
        u1 = Rand(self.seed).eval(ctx).data
        u2 = Rand(self.seed + 0x5DEECE66D).eval(ctx).data
        u1 = xp.maximum(u1, 1e-12)
        out = xp.sqrt(-2.0 * xp.log(u1)) * xp.cos(2.0 * math.pi * u2)
        return ExprValue(out, None)

    def __repr__(self):
        return f"randn({self.seed})"


class SparkPartitionId(Expression):
    """spark_partition_id(): the mesh shard index in distributed execution;
    0 on the single-chip path (set via ExecContext.partition_id)."""

    children = ()

    def data_type(self, schema):
        return T.int32

    @property
    def name(self):
        return "SPARK_PARTITION_ID()"

    def eval(self, ctx):
        xp = ctx.xp
        # distributed execution encodes the mesh shard in the high bits of
        # the row offset (executor.py: shard_offset = axis_index << 48);
        # single-chip offsets stay below 2^48 → partition 0
        offset = getattr(ctx, "row_offset", 0)
        if isinstance(offset, int):
            pid = np.int32(offset >> 48)
            return ExprValue(xp.asarray(pid), None)
        return ExprValue((offset >> 48).astype(np.int32), None)

    def __repr__(self):
        return "spark_partition_id()"


# ---------------------------------------------------------------------------
# Array expressions (`complexTypeCreator.scala`, `collectionOperations.scala`)
#
# Layout contract: see T.ArrayType — (capacity, max_len) element-dtype data
# with trailing sentinel padding; element order is position order.
# ---------------------------------------------------------------------------

def _array_elem_mask(xp, dt: "T.ArrayType", data):
    s = dt.element_sentinel()
    if dt.element_type.is_fractional:
        return ~xp.isnan(data)
    return data != s


class MakeArray(Expression):
    """array(e1, e2, ...): fixed-length array from scalar expressions."""

    def __init__(self, *children: Expression):
        if not children:
            raise AnalysisException("array() needs at least one element")
        self.children = tuple(children)

    def map_children(self, fn):
        return MakeArray(*[fn(c) for c in self.children])

    @property
    def name(self):
        return f"array({', '.join(c.name for c in self.children)})"

    def data_type(self, schema):
        et = self.children[0].data_type(schema)
        for c in self.children[1:]:
            et = T.numeric_promote(et, c.data_type(schema)) \
                if et != c.data_type(schema) else et
        return T.ArrayType(et)

    def eval(self, ctx):
        from .columnar import merge_dictionaries
        xp = ctx.xp
        dt = self.data_type(ctx.batch.schema)
        ed = dt.element_type.np_dtype
        vals = [ctx.broadcast(c.eval(ctx)) for c in self.children]
        sent = dt.element_sentinel()
        out_dict = None
        if dt.element_type.is_string:
            # merge each element's dictionary into one shared code space
            merged = vals[0].dictionary or ("",)
            remaps = [np.arange(len(merged), dtype=np.int32)]
            for v in vals[1:]:
                merged, ra, rb = merge_dictionaries(
                    merged, v.dictionary or ("",))
                remaps = [ra[r] for r in remaps] + [rb]
            vals = [ExprValue(xp.asarray(r)[xp.clip(v.data, 0, None)],
                              v.valid, merged)
                    for v, r in zip(vals, remaps)]
            out_dict = merged
        cols = []
        masks = []
        any_null = any(v.valid is not None for v in vals)
        for v in vals:
            d = v.data.astype(ed)
            if v.valid is not None:          # NULL element -> sentinel slot
                d = xp.where(v.valid, d, sent)
                masks.append(v.valid)
            else:
                masks.append(None)
            cols.append(d)
        data = xp.stack(cols, axis=-1)
        if any_null:
            # pack live elements to the FRONT: the ArrayType layout is
            # position-packed with trailing sentinels (ElementAt/size
            # depend on it).  Deviation: NULL elements are dropped, not
            # kept in place — interior nulls are unrepresentable here.
            k = len(cols)
            mask = xp.stack(
                [m if m is not None
                 else xp.ones(data.shape[0], bool) for m in masks], axis=-1)
            order = xp.argsort(~mask, axis=-1, stable=True)
            data = xp.take_along_axis(data, order, axis=-1)
        return ExprValue(data, None, out_dict)

    def __repr__(self):
        return f"array({', '.join(map(repr, self.children))})"


class SplitStr(Expression):
    """split(str, regex[, limit]) -> array<string>: the dictionary is
    split on host once per trace; the device gathers per-row element-code
    vectors from a (dict_size, max_len) table."""

    def __init__(self, child: Expression, pattern: str, limit: int = -1):
        self.pattern = pattern
        self.limit = limit
        self.children = (child,)

    def map_children(self, fn):
        return SplitStr(fn(self.children[0]), self.pattern, self.limit)

    @property
    def name(self):
        return f"split({self.children[0].name}, {self.pattern!r})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not ct.is_string:
            raise AnalysisException(f"split expects string, got {ct}")
        return T.ArrayType(T.string)

    def eval(self, ctx):
        import re as re_mod
        xp = ctx.xp
        v = self.children[0].eval(ctx)
        rx = re_mod.compile(self.pattern)
        # re.split maxsplit: 0 = unlimited; Spark limit<=0 = split fully
        maxsplit = 0 if self.limit <= 0 else self.limit - 1
        parts_per_word = [rx.split(w, maxsplit)
                          for w in (v.dictionary or ("",))]
        elem_dict = tuple(sorted({p for parts in parts_per_word
                                  for p in parts}))
        pos = {w: i for i, w in enumerate(elem_dict)}
        L = max(max((len(p) for p in parts_per_word), default=1), 1)
        table = np.full((len(parts_per_word), L), -1, np.int32)
        for i, parts in enumerate(parts_per_word):
            for j, p in enumerate(parts):
                table[i, j] = pos[p]
        codes = xp.clip(v.data, 0, None)
        return ExprValue(xp.asarray(table)[codes], v.valid, elem_dict)

    def __repr__(self):
        return f"split({self.children[0]!r}, {self.pattern!r})"


class ArraySize(Expression):
    """size(arr): element count (0 for empty; NULL row follows row mask)."""

    def __init__(self, child: Expression):
        self.children = (child,)

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if isinstance(ct, T.MapType):
            return T.int32        # size(map): rewritten to its keys plane
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"size expects an array, got {ct}")
        return T.int32

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        return ExprValue(mask.sum(axis=-1).astype(np.int32), v.valid)

    def __repr__(self):
        return f"size({self.children[0]!r})"


def _gather_1based_plane(xp, dt, v, idx, capacity, out_np_dtype):
    """ONE definition of the 1-based (negative = from-the-end, 0/out of
    bounds = NULL) array-plane gather, shared by ElementAt (static index)
    and ArrayGather (dynamic index) so their semantics cannot diverge.
    Returns (gathered data, ok mask)."""
    if v.data.shape[-1] == 0:        # all-empty plane: nothing to gather
        return xp.zeros(capacity, out_np_dtype), xp.zeros(capacity, bool)
    mask = _array_elem_mask(xp, dt, v.data)
    lengths = mask.sum(axis=-1)
    eff = xp.where(idx > 0, idx - 1, lengths + idx)
    ok = (idx != 0) & (eff >= 0) & (eff < lengths)
    gathered = xp.take_along_axis(
        v.data, xp.clip(eff, 0, v.data.shape[-1] - 1)[..., None],
        axis=-1)[..., 0]
    return gathered, ok


class ElementAt(Expression):
    """element_at(arr, i): 1-based; negative indexes from the end; out of
    bounds -> NULL (Spark's non-ANSI behavior)."""

    def __init__(self, child: Expression, index: int):
        if index == 0:
            raise AnalysisException("element_at index is 1-based; got 0")
        self.index = int(index)
        self.children = (child,)

    def map_children(self, fn):
        return ElementAt(fn(self.children[0]), self.index)

    @property
    def name(self):
        return f"element_at({self.children[0].name}, {self.index})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if isinstance(ct, T.MapType):
            return ct.value_type  # element_at(map, k): rewritten to MapGet
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"element_at expects an array, got {ct}")
        return ct.element_type

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        out_dt = self.data_type(ctx.batch.schema).np_dtype
        gathered, ok = _gather_1based_plane(
            xp, dt, v, np.int64(self.index), ctx.capacity, out_dt)
        return ExprValue(gathered, and_valid(xp, v.valid, ok),
                         v.dictionary)

    def __repr__(self):
        return f"element_at({self.children[0]!r}, {self.index})"


class ArrayReduce(Expression):
    """array_max / array_min: sentinel-aware reduction over the plane."""

    def __init__(self, child: Expression, op: str):
        self.children = (child,)
        self.op = op                      # "max" | "min"

    def map_children(self, fn):
        return ArrayReduce(fn(self.children[0]), self.op)

    @property
    def name(self):
        return f"array_{self.op}({self.children[0].name})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(
                f"array_{self.op} expects an array, got {ct}")
        return ct.element_type

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        et = dt.element_type
        if et.is_fractional:
            lo, hi = -np.inf, np.inf
        else:
            info = np.iinfo(et.np_dtype)
            lo, hi = info.min, info.max
        fill = lo if self.op == "max" else hi
        red = xp.max if self.op == "max" else xp.min
        out = red(xp.where(mask, v.data, fill), axis=-1)
        nonempty = mask.any(axis=-1)
        return ExprValue(out, and_valid(xp, v.valid, nonempty),
                         v.dictionary)

    def __repr__(self):
        return f"array_{self.op}({self.children[0]!r})"


class SortArray(Expression):
    """sort_array(arr[, asc]): per-row element sort, dead slots kept as a
    trailing sentinel block (live-prefix layout contract)."""

    def __init__(self, child: Expression, asc: bool = True):
        self.children = (child,)
        self.asc = bool(asc)

    def map_children(self, fn):
        return SortArray(fn(self.children[0]), self.asc)

    @property
    def name(self):
        return f"sort_array({self.children[0].name}, {self.asc})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"sort_array expects an array, got {ct}")
        return ct

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        et = dt.element_type
        # string codes sort lexicographically BY CONSTRUCTION (sorted
        # dictionaries).  Ascending: dead slots carry the MAX extreme so
        # they sink; descending: dead slots carry the MIN extreme, sort
        # ascending, then flip the row — dead slots land last either way
        # with no negation (which would overflow int64 / lose exactness).
        if et.is_fractional:
            info_lo, info_hi = -np.inf, np.inf
        else:
            info = np.iinfo(et.np_dtype)
            info_lo, info_hi = info.min, info.max
        fill = info_hi if self.asc else info_lo
        order = xp.argsort(xp.where(mask, v.data, fill), axis=-1,
                           stable=True)
        if not self.asc:
            order = xp.flip(order, axis=-1)
        data = xp.take_along_axis(v.data, order, axis=-1)
        smask = xp.take_along_axis(mask, order, axis=-1)
        data = xp.where(smask, data, dt.element_sentinel())
        return ExprValue(data, v.valid, v.dictionary)

    def __repr__(self):
        return f"sort_array({self.children[0]!r}, asc={self.asc})"


class ArrayDistinct(Expression):
    """array_distinct(arr): first occurrence of each element kept, order
    preserved, result compacted to the live prefix."""

    def __init__(self, child: Expression):
        self.children = (child,)

    @property
    def name(self):
        return f"array_distinct({self.children[0].name})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(
                f"array_distinct expects an array, got {ct}")
        return ct

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        # first-occurrence: element j survives iff no earlier equal live
        # element exists — O(L^2) pairwise plane, L is small and static
        eq = v.data[..., :, None] == v.data[..., None, :]
        earlier = xp.tril(xp.ones(eq.shape[-2:], bool), k=-1)
        dup = (eq & earlier & mask[..., None, :]
               & mask[..., :, None]).any(axis=-1)
        keep = mask & ~dup
        order = xp.argsort(~keep, axis=-1, stable=True)
        data = xp.take_along_axis(v.data, order, axis=-1)
        kept = xp.take_along_axis(keep, order, axis=-1)
        data = xp.where(kept, data, dt.element_sentinel())
        return ExprValue(data, v.valid, v.dictionary)

    def __repr__(self):
        return f"array_distinct({self.children[0]!r})"


class ArraySlice(Expression):
    """slice(arr, start, length): 1-based, negative start from the end."""

    def __init__(self, child: Expression, start: int, length: int):
        if start == 0:
            raise AnalysisException("slice start is 1-based; got 0")
        if length < 0:
            raise AnalysisException("slice length must be >= 0")
        self.children = (child,)
        self.start = int(start)
        self.length = int(length)

    def map_children(self, fn):
        return ArraySlice(fn(self.children[0]), self.start, self.length)

    @property
    def name(self):
        return f"slice({self.children[0].name}, {self.start}, {self.length})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"slice expects an array, got {ct}")
        return ct

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        lengths = mask.sum(axis=-1)
        width = v.data.shape[-1]
        begin = np.int64(self.start)
        eff = xp.where(begin > 0, begin - 1, lengths + begin)
        # Spark: a negative start reaching before element 0 yields the
        # EMPTY array (never a partial tail), and live elements must land
        # on the output PREFIX (layout contract)
        valid_start = (eff >= 0) & (eff < lengths)
        pos = xp.arange(width, dtype=np.int64)
        idx = eff[..., None] + pos
        in_range = valid_start[..., None] & (pos < self.length) \
            & (idx < lengths[..., None])
        gathered = xp.take_along_axis(
            v.data, xp.clip(idx, 0, width - 1), axis=-1)
        data = xp.where(in_range, gathered, dt.element_sentinel())
        return ExprValue(data, v.valid, v.dictionary)

    def __repr__(self):
        return f"slice({self.children[0]!r}, {self.start}, {self.length})"


class ArrayPosition(Expression):
    """array_position(arr, value): 1-based first index, 0 when absent."""

    def __init__(self, child: Expression, value: Any):
        self.children = (child,)
        self.value = value

    def map_children(self, fn):
        return ArrayPosition(fn(self.children[0]), self.value)

    @property
    def name(self):
        return f"array_position({self.children[0].name}, {self.value!r})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(
                f"array_position expects an array, got {ct}")
        return T.int64

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        if dt.element_type.is_string:
            if v.dictionary is None or self.value not in v.dictionary:
                hit = xp.zeros(v.data.shape, bool)
            else:
                hit = v.data == v.dictionary.index(self.value)
        else:
            hit = v.data == np.asarray(self.value).astype(
                dt.element_type.np_dtype)
        hit = hit & mask
        width = v.data.shape[-1]
        first = xp.where(hit, xp.arange(width, dtype=np.int64),
                         np.int64(width)).min(axis=-1)
        pos = xp.where(first < width, first + 1, 0)
        return ExprValue(pos, v.valid)

    def __repr__(self):
        return f"array_position({self.children[0]!r}, {self.value!r})"


class LambdaVar(Expression):
    """Lambda placeholder bound by a higher-order array function to the
    ELEMENT PLANE (`higherOrderFunctions.scala`'s NamedLambdaVariable).

    Evaluates to the whole ``(capacity, max_len)`` plane — element-wise
    lambdas become plain vectorized ops over it, which is exactly the
    TPU-friendly shape.  ``dtype`` is bound by the enclosing function at
    type-resolution time (deterministic, planning-only mutation)."""

    _counter = [0]

    def __init__(self, name: str = "x"):
        self.children = ()
        LambdaVar._counter[0] += 1
        self._name = f"{name}#{LambdaVar._counter[0]}"
        self.dtype: Optional[T.DataType] = None
        self.dictionary = None

    @property
    def name(self):
        return self._name

    def references(self) -> set:
        return set()                   # bound, not a column reference

    def data_type(self, schema):
        if self.dtype is None:
            raise AnalysisException(
                f"lambda variable {self._name} used outside its "
                "higher-order function")
        return self.dtype

    def eval(self, ctx):
        bound = getattr(ctx, "lambda_bindings", {}).get(self._name)
        if bound is None:
            raise AnalysisException(
                f"lambda variable {self._name} evaluated without a "
                "binding")
        return bound

    def __repr__(self):
        return self._name.split("#")[0]


class _HigherOrder(Expression):
    """Shared machinery: bind the element plane, evaluate the body
    vectorized over it."""

    def __init__(self, child: Expression, var: LambdaVar, body: Expression):
        self.children = (child,)
        self.var = var
        self.body = body
        extra = body.references()
        if extra:
            raise AnalysisException(
                f"lambda body may reference only the lambda variable and "
                f"literals in this engine (vectorized element-plane "
                f"evaluation); found column refs {sorted(extra)}")

    def map_children(self, fn):
        return type(self)(fn(self.children[0]), self.var, self.body)

    def _array_type(self, schema) -> "T.ArrayType":
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(
                f"{type(self).__name__} expects an array, got {ct}")
        self.var.dtype = ct.element_type
        return ct

    def _plane(self, ctx):
        """(value ExprValue over the plane, element mask, array ExprValue)."""
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        self.var.dtype = dt.element_type
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        bound = ExprValue(v.data, None, v.dictionary)
        bindings = dict(getattr(ctx, "lambda_bindings", {}))
        bindings[self.var._name] = bound
        sub = EvalContext(ctx.batch, xp)
        sub.lambda_bindings = bindings
        out = self.body.eval(sub)
        return out, mask, v


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> expr): elementwise map over the plane."""

    @property
    def name(self):
        return f"transform({self.children[0].name}, " \
               f"{self.var!r} -> {self.body.name})"

    def data_type(self, schema):
        self._array_type(schema)
        et = self.body.data_type(schema)
        if et.is_string:
            raise AnalysisException(
                "transform to string elements is not supported yet")
        if isinstance(et, T.BooleanType):
            et = T.int32           # bool arrays have no sentinel; widen
        return T.ArrayType(et)

    def eval(self, ctx):
        xp = ctx.xp
        out, mask, v = self._plane(ctx)
        odt = self.data_type(ctx.batch.schema)
        sent = odt.element_sentinel()
        data = xp.asarray(out.data).astype(odt.element_type.np_dtype)
        ok = mask if out.valid is None else (mask & out.valid)
        data = xp.where(ok, data, sent)
        return ExprValue(data, v.valid)

    def __repr__(self):
        return f"transform({self.children[0]!r}, {self.var!r} -> " \
               f"{self.body!r})"


class ArrayFilterFn(_HigherOrder):
    """filter(arr, x -> pred): keep matching elements, COMPACTED to a
    prefix (positional ops like element_at assume live-prefix layout)."""

    @property
    def name(self):
        return f"filter({self.children[0].name}, " \
               f"{self.var!r} -> {self.body.name})"

    def data_type(self, schema):
        ct = self._array_type(schema)
        bt = self.body.data_type(schema)
        if not isinstance(bt, T.BooleanType):
            raise AnalysisException(
                f"filter lambda must return boolean, got {bt} "
                f"({self.body!r})")
        return ct

    def eval(self, ctx):
        xp = ctx.xp
        out, mask, v = self._plane(ctx)
        dt = self.children[0].data_type(ctx.batch.schema)
        sent = dt.element_sentinel()
        pred = xp.asarray(out.data).astype(bool)
        if out.valid is not None:
            pred = pred & out.valid
        keep = mask & pred
        # stable compaction: live elements first, original order kept
        # (same idiom as MakeArray's null compaction)
        order = xp.argsort(~keep, axis=-1, stable=True)
        data = xp.take_along_axis(v.data, order, axis=-1)
        kept = xp.take_along_axis(keep, order, axis=-1)
        data = xp.where(kept, data, sent)
        return ExprValue(data, v.valid, v.dictionary)

    def __repr__(self):
        return f"filter({self.children[0]!r}, {self.var!r} -> " \
               f"{self.body!r})"


class ArrayExists(_HigherOrder):
    """exists(arr, x -> pred) / forall(arr, x -> pred)."""

    def __init__(self, child, var, body, require_all: bool = False):
        super().__init__(child, var, body)
        self.require_all = require_all

    def map_children(self, fn):
        return ArrayExists(fn(self.children[0]), self.var, self.body,
                           self.require_all)

    @property
    def name(self):
        kind = "forall" if self.require_all else "exists"
        return f"{kind}({self.children[0].name}, " \
               f"{self.var!r} -> {self.body.name})"

    def data_type(self, schema):
        self._array_type(schema)
        bt = self.body.data_type(schema)
        if not isinstance(bt, T.BooleanType):
            kind = "forall" if self.require_all else "exists"
            raise AnalysisException(
                f"{kind} lambda must return boolean, got {bt} "
                f"({self.body!r})")
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        out, mask, v = self._plane(ctx)
        pred = xp.asarray(out.data).astype(bool)
        if out.valid is not None:
            pred = pred & out.valid
        if self.require_all:
            res = xp.all(pred | ~mask, axis=-1)
        else:
            res = xp.any(pred & mask, axis=-1)
        return ExprValue(res, v.valid)

    def __repr__(self):
        kind = "forall" if self.require_all else "exists"
        return f"{kind}({self.children[0]!r}, {self.var!r} -> " \
               f"{self.body!r})"


class ArrayAggregate(Expression):
    """aggregate(arr, init, (acc, x) -> merge[, acc -> finish]): fold over
    the element plane.  The fold unrolls over the STATIC max_len (one
    masked select per slot — compiler-friendly, no data-dependent loop)."""

    def __init__(self, child: Expression, init: Expression,
                 acc_var: "LambdaVar", x_var: "LambdaVar",
                 merge: Expression,
                 finish_var: Optional["LambdaVar"] = None,
                 finish: Optional[Expression] = None):
        self.children = (child, init)
        self.acc_var = acc_var
        self.x_var = x_var
        self.merge = merge
        self.finish_var = finish_var
        self.finish = finish
        for body in (merge, finish):
            if body is not None and body.references():
                raise AnalysisException(
                    "lambda body may reference only its lambda variables "
                    "and literals in this engine; found column refs "
                    f"{sorted(body.references())}")

    def map_children(self, fn):
        return ArrayAggregate(fn(self.children[0]), fn(self.children[1]),
                              self.acc_var, self.x_var, self.merge,
                              self.finish_var, self.finish)

    @property
    def name(self):
        return f"aggregate({self.children[0].name})"

    def _bind_types(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"aggregate expects an array, got {ct}")
        self.x_var.dtype = ct.element_type
        self.acc_var.dtype = self.children[1].data_type(schema)
        return ct

    def data_type(self, schema):
        self._bind_types(schema)
        if self.acc_var.dtype.is_string:
            raise AnalysisException(
                "aggregate with a string accumulator is not supported "
                "yet (dictionary state cannot thread through the fold)")
        mt = self.merge.data_type(schema)
        if mt.is_string:
            raise AnalysisException(
                "aggregate merge producing strings is not supported yet")
        if self.finish is not None:
            self.finish_var.dtype = mt
            return self.finish.data_type(schema)
        return mt

    def eval(self, ctx):
        xp = ctx.xp
        dt = self._bind_types(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        init = ctx.broadcast(self.children[1].eval(ctx))
        acc_data = init.data
        acc_valid = init.valid
        width = v.data.shape[-1]
        for i in range(width):
            sub = EvalContext(ctx.batch, xp)
            sub.lambda_bindings = dict(getattr(ctx, "lambda_bindings", {}))
            sub.lambda_bindings[self.acc_var._name] = \
                ExprValue(acc_data, acc_valid)
            sub.lambda_bindings[self.x_var._name] = \
                ExprValue(v.data[..., i], None, v.dictionary)
            merged = sub.broadcast(self.merge.eval(sub))
            live = mask[..., i]
            acc_data = xp.where(live, merged.data, acc_data)
            if merged.valid is not None or acc_valid is not None:
                mv = merged.valid if merged.valid is not None \
                    else xp.ones_like(live)
                av = acc_valid if acc_valid is not None \
                    else xp.ones_like(live)
                acc_valid = xp.where(live, mv, av)
        out = ExprValue(acc_data, and_valid(xp, v.valid, acc_valid)
                        if acc_valid is not None else v.valid)
        if self.finish is not None:
            self.finish_var.dtype = self.merge.data_type(ctx.batch.schema)
            sub = EvalContext(ctx.batch, xp)
            sub.lambda_bindings = {self.finish_var._name: out}
            fin = sub.broadcast(self.finish.eval(sub))
            out = ExprValue(fin.data,
                            and_valid(xp, out.valid, fin.valid)
                            if fin.valid is not None else out.valid)
        return out

    def __repr__(self):
        fin = f", {self.finish_var!r} -> {self.finish!r}" \
            if self.finish is not None else ""
        return (f"aggregate({self.children[0]!r}, {self.children[1]!r}, "
                f"({self.acc_var!r}, {self.x_var!r}) -> "
                f"{self.merge!r}{fin})")


class ZipWith(Expression):
    """zip_with(a, b, (x, y) -> expr): elementwise combine of two arrays.
    The shorter side's missing tail enters the lambda as NULL (validity
    propagation), matching the reference's null-padded zip."""

    def __init__(self, left: Expression, right: Expression,
                 x_var: "LambdaVar", y_var: "LambdaVar", body: Expression):
        self.children = (left, right)
        self.x_var = x_var
        self.y_var = y_var
        self.body = body
        if body.references():
            raise AnalysisException(
                "lambda body may reference only its lambda variables and "
                f"literals; found column refs {sorted(body.references())}")

    def map_children(self, fn):
        return ZipWith(fn(self.children[0]), fn(self.children[1]),
                       self.x_var, self.y_var, self.body)

    @property
    def name(self):
        return f"zip_with({self.children[0].name}, {self.children[1].name})"

    def _bind_types(self, schema):
        lt = self.children[0].data_type(schema)
        rt = self.children[1].data_type(schema)
        if not isinstance(lt, T.ArrayType) or not isinstance(rt, T.ArrayType):
            raise AnalysisException(
                f"zip_with expects two arrays, got {lt} and {rt}")
        self.x_var.dtype = lt.element_type
        self.y_var.dtype = rt.element_type
        return lt, rt

    def data_type(self, schema):
        self._bind_types(schema)
        et = self.body.data_type(schema)
        if et.is_string:
            raise AnalysisException(
                "zip_with to string elements is not supported yet")
        if isinstance(et, T.BooleanType):
            et = T.int32
        return T.ArrayType(et)

    def eval(self, ctx):
        xp = ctx.xp
        lt, rt = self._bind_types(ctx.batch.schema)
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        am = _array_elem_mask(xp, lt, a.data)
        bm = _array_elem_mask(xp, rt, b.data)
        wa, wb = a.data.shape[-1], b.data.shape[-1]
        w = max(wa, wb)

        def widen(data, mask, width, fill):
            if width == w:
                return data, mask
            pad = [(0, 0)] * (data.ndim - 1) + [(0, w - width)]
            return (xp.pad(data, pad, constant_values=fill),
                    xp.pad(mask, pad, constant_values=False))

        ad, am = widen(a.data, am, wa, 0)
        bd, bm = widen(b.data, bm, wb, 0)
        sub = EvalContext(ctx.batch, xp)
        sub.lambda_bindings = dict(getattr(ctx, "lambda_bindings", {}))
        sub.lambda_bindings[self.x_var._name] = \
            ExprValue(ad, am, a.dictionary)
        sub.lambda_bindings[self.y_var._name] = \
            ExprValue(bd, bm, b.dictionary)
        out = self.body.eval(sub)
        odt = self.data_type(ctx.batch.schema)
        sent = odt.element_sentinel()
        live = am | bm
        ok = live if out.valid is None else (live & out.valid)
        data = xp.where(ok, xp.asarray(out.data).astype(
            odt.element_type.np_dtype), sent)
        return ExprValue(data, and_valid(xp, a.valid, b.valid))

    def __repr__(self):
        return (f"zip_with({self.children[0]!r}, {self.children[1]!r}, "
                f"({self.x_var!r}, {self.y_var!r}) -> {self.body!r})")


class ArrayContains(Expression):
    """array_contains(arr, literal)."""

    def __init__(self, child: Expression, value: Any):
        self.value = value
        self.children = (child,)

    def map_children(self, fn):
        return ArrayContains(fn(self.children[0]), self.value)

    @property
    def name(self):
        return f"array_contains({self.children[0].name}, {self.value!r})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(
                f"array_contains expects an array, got {ct}")
        return T.boolean

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        mask = _array_elem_mask(xp, dt, v.data)
        if dt.element_type.is_string:
            words = np.array(v.dictionary or (), dtype=object)
            idx = int(np.searchsorted(words, self.value)) if len(words) \
                else 0
            if idx >= len(words) or words[idx] != self.value:
                zero = xp.zeros(v.data.shape[0], bool)
                return ExprValue(zero, v.valid)
            target = np.int32(idx)
        else:
            ed = np.dtype(dt.element_type.np_dtype)
            if np.issubdtype(ed, np.integer) and \
                    float(self.value) != int(self.value):
                # 1.5 can never equal an integer element; casting would
                # truncate and false-positive
                return ExprValue(xp.zeros(v.data.shape[0], bool), v.valid)
            target = np.asarray(self.value, ed)
        hit = ((v.data == target) & mask).any(axis=-1)
        return ExprValue(hit, v.valid)

    def __repr__(self):
        return f"array_contains({self.children[0]!r}, {self.value!r})"


class ExplodeMarker(Expression):
    """Marker for explode()/posexplode() in a select list; the DataFrame/
    analyzer layer rewrites it into the Explode logical operator (the
    reference's `Generate` + `GeneratorOuter` machinery collapsed to the
    one generator the columnar engine supports)."""

    def __init__(self, child: Expression, with_pos: bool = False):
        self.with_pos = with_pos
        self.children = (child,)

    def map_children(self, fn):
        return ExplodeMarker(fn(self.children[0]), self.with_pos)

    @property
    def name(self):
        return "col" if not self.with_pos else "posexplode"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"explode expects an array, got {ct}")
        return ct.element_type

    def eval(self, ctx):
        raise AnalysisException(
            "explode() is only supported as a top-level select expression")

    def __repr__(self):
        return f"explode({self.children[0]!r})"


class GroupingCall(Expression):
    """grouping(col) / grouping_id() inside GROUP BY ROLLUP/CUBE/GROUPING
    SETS — resolved to per-branch literals by the analyzer's grouping-sets
    rewrite (`grouping__id` in the reference's Expand output)."""

    def __init__(self, child: Optional[Expression]):
        self.children = (child,) if child is not None else ()

    @property
    def name(self):
        return "grouping_id()" if not self.children \
            else f"grouping({self.children[0].name})"

    def data_type(self, schema):
        return T.int64 if not self.children else T.int32

    def eval(self, ctx):
        raise AnalysisException(
            "grouping()/grouping_id() are only valid with GROUP BY "
            "ROLLUP/CUBE/GROUPING SETS")

    def __repr__(self):
        return self.name


# ---------------------------------------------------------------------------
# complex types: struct + map (the object layer)
# ---------------------------------------------------------------------------
#
# Maps and structs are OBJECT-LAYER values, exactly as in the reference
# (`complexTypeCreator.scala:164` CreateMap/CreateNamedStruct never got a
# Tungsten-vectorized layout): every consumer is rewritten by the optimizer
# into flat array/scalar expressions (`SimplifyExtractValueOps`-style,
# `complexTypeExtractors.scala`), so nothing below ever materializes a
# nested value on device.  Only a COLLECTED map/struct column materializes,
# as its flat planes (docs/DECISIONS.md pair-of-planes design), zipped into
# Python dicts/Rows host-side by the DataFrame layer.

_COMPLEX_EVAL_HINT = (
    " survived to execution: complex values are consumed via "
    "getField/map_keys/map_values/element_at/size (rewritten to flat "
    "columns by the optimizer) or collected at the top level.  A map/"
    "struct flowing through an operator that is neither is unsupported — "
    "as are maps/structs read from files (docs/DECISIONS.md)."
)


class CreateStruct(Expression):
    """struct(...) / named_struct(...) — `complexTypeCreator.scala:164`."""

    def __init__(self, field_names, *children: Expression):
        if not children or len(field_names) != len(children):
            raise AnalysisException("struct() needs one name per field")
        self.field_names = tuple(field_names)
        self.children = tuple(children)

    def map_children(self, fn):
        return CreateStruct(self.field_names,
                            *[fn(c) for c in self.children])

    @property
    def name(self):
        return f"struct({', '.join(c.name for c in self.children)})"

    def data_type(self, schema):
        return T.StructType([T.StructField(n, c.data_type(schema))
                             for n, c in zip(self.field_names,
                                             self.children)])

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        parts = [f"{n}={c!r}" for n, c in zip(self.field_names,
                                              self.children)]
        return f"named_struct({', '.join(parts)})"


class GetField(Expression):
    """struct.field — `complexTypeExtractors.scala` GetStructField."""

    def __init__(self, child: Expression, field: str):
        self.children = (child,)
        self.field = field

    def map_children(self, fn):
        return GetField(fn(self.children[0]), self.field)

    @property
    def name(self):
        return self.field

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.StructType):
            raise AnalysisException(
                f"getField expects a struct, got {ct}")
        for f in ct.fields:
            if f.name == self.field:
                return f.dataType
        raise AnalysisException(
            f"no field {self.field!r} in {ct.names}")

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.field}"


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) — `complexTypeCreator.scala` CreateMap."""

    def __init__(self, *children: Expression):
        if not children or len(children) % 2:
            raise AnalysisException(
                "map() needs an even, positive number of arguments "
                "(alternating keys and values)")
        self.children = tuple(children)

    def map_children(self, fn):
        return CreateMap(*[fn(c) for c in self.children])

    @property
    def keys(self):
        return self.children[0::2]

    @property
    def values(self):
        return self.children[1::2]

    @property
    def name(self):
        return f"map({', '.join(c.name for c in self.children)})"

    def _common(self, exprs, schema, what):
        dt = exprs[0].data_type(schema)
        for e in exprs[1:]:
            nxt = T.common_type(dt, e.data_type(schema))
            if nxt is None:
                raise AnalysisException(
                    f"map {what} types are incompatible: {dt} vs "
                    f"{e.data_type(schema)}")
            dt = nxt
        return dt

    def data_type(self, schema):
        return T.MapType(self._common(self.keys, schema, "key"),
                         self._common(self.values, schema, "value"))

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return f"map({', '.join(repr(c) for c in self.children)})"


class MapFromArrays(Expression):
    """map_from_arrays(keys_array, values_array)."""

    def __init__(self, keys: Expression, values: Expression):
        self.children = (keys, values)

    def map_children(self, fn):
        return MapFromArrays(fn(self.children[0]), fn(self.children[1]))

    @property
    def name(self):
        return (f"map_from_arrays({self.children[0].name}, "
                f"{self.children[1].name})")

    def data_type(self, schema):
        kt = self.children[0].data_type(schema)
        vt = self.children[1].data_type(schema)
        if not isinstance(kt, T.ArrayType) or not isinstance(vt, T.ArrayType):
            raise AnalysisException(
                f"map_from_arrays expects two arrays, got {kt}, {vt}")
        return T.MapType(kt.element_type, vt.element_type)

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return (f"map_from_arrays({self.children[0]!r}, "
                f"{self.children[1]!r})")


class _MapExtract(Expression):
    """Shared shape of map_keys/map_values."""

    WHICH = "keys"

    def __init__(self, child: Expression):
        self.children = (child,)

    def map_children(self, fn):
        return type(self)(fn(self.children[0]))

    @property
    def name(self):
        return f"map_{self.WHICH}({self.children[0].name})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.MapType):
            raise AnalysisException(
                f"map_{self.WHICH} expects a map, got {ct}")
        return T.ArrayType(ct.key_type if self.WHICH == "keys"
                           else ct.value_type)

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return f"map_{self.WHICH}({self.children[0]!r})"


class MapKeys(_MapExtract):
    WHICH = "keys"


class MapValues(_MapExtract):
    WHICH = "values"


class MapGet(Expression):
    """map[key] / element_at(map, key) — GetMapValue: NULL when absent."""

    def __init__(self, child: Expression, key: Expression):
        self.children = (child, key)

    def map_children(self, fn):
        return MapGet(fn(self.children[0]), fn(self.children[1]))

    @property
    def name(self):
        return f"element_at({self.children[0].name}, {self.children[1].name})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if isinstance(ct, T.ArrayType):
            return ct.element_type    # dynamic element_at(arr, expr):
        if not isinstance(ct, T.MapType):  # rewritten to ArrayGather
            raise AnalysisException(f"element_at on {ct} needs a map")
        return ct.value_type

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return f"element_at({self.children[0]!r}, {self.children[1]!r})"


class GetItem(Expression):
    """Column.getItem(key): 0-based position for arrays, key for maps —
    `complexTypeExtractors.scala` ExtractValue dispatch, resolved by the
    optimizer's complex-type rewrite once the child's type is known."""

    def __init__(self, child: Expression, key):
        self.children = (child,)
        self.key = key

    def map_children(self, fn):
        return GetItem(fn(self.children[0]), self.key)

    @property
    def name(self):
        return f"{self.children[0].name}[{self.key!r}]"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if isinstance(ct, T.ArrayType):
            return ct.element_type
        if isinstance(ct, T.MapType):
            return ct.value_type
        if isinstance(ct, T.StructType) and isinstance(self.key, str):
            return GetField(self.children[0], self.key).data_type(schema)
        raise AnalysisException(f"getItem on {ct} is not supported")

    def eval(self, ctx):
        raise AnalysisException(f"{self!r}" + _COMPLEX_EVAL_HINT)

    def __repr__(self):
        return f"{self.children[0]!r}[{self.key!r}]"


class ArrayGather(Expression):
    """1-based dynamic-position gather from an array plane; position 0 or
    out of bounds -> NULL.  The flat form MapGet(map_from_arrays(k, v), x)
    rewrites into (via array_position) — and a real dual-path eval, since
    it is what actually executes."""

    def __init__(self, arr: Expression, pos: Expression):
        self.children = (arr, pos)

    def map_children(self, fn):
        return ArrayGather(fn(self.children[0]), fn(self.children[1]))

    @property
    def name(self):
        return f"element_at({self.children[0].name}, {self.children[1].name})"

    def data_type(self, schema):
        ct = self.children[0].data_type(schema)
        if not isinstance(ct, T.ArrayType):
            raise AnalysisException(f"array gather expects an array, got {ct}")
        return ct.element_type

    def eval(self, ctx):
        xp = ctx.xp
        dt = self.children[0].data_type(ctx.batch.schema)
        v = self.children[0].eval(ctx)
        p = ctx.broadcast(self.children[1].eval(ctx))
        out_dt = self.data_type(ctx.batch.schema).np_dtype
        gathered, ok = _gather_1based_plane(
            xp, dt, v, p.data.astype(np.int64), ctx.capacity, out_dt)
        valid = and_valid(xp, and_valid(xp, v.valid, p.valid), ok)
        return ExprValue(gathered, valid, v.dictionary)

    def __repr__(self):
        return f"array_gather({self.children[0]!r}, {self.children[1]!r})"
