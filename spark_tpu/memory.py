"""HBM memory accounting and the device cache manager.

The reference splits a fixed heap between EXECUTION (shuffle/sort/join
working memory) and STORAGE (cached blocks), with storage evictable down
to a protected floor — ``UnifiedMemoryManager.scala:47`` — and tracks
cached relations in ``CacheManager.scala`` / ``InMemoryRelation.scala``
with compressed column blocks and LRU-style eviction via the
``BlockManager``/``MemoryStore``.

TPU translation:

- the accounted resource is device HBM.  The budget comes from the live
  device (``Device.memory_stats()['bytes_limit']``) when the backend
  exposes it, else ``spark.tpu.memory.hbmBudget``.
- EXECUTION reservations are made by the planner for a query's leaf
  batches + operator working set *before* dispatch, so an impossible
  query fails with an honest ``HBMOutOfMemoryError`` naming the reserver
  instead of an opaque XLA allocation crash.
- STORAGE holds cached relations as device-resident ColumnBatches.
  Under pressure they demote: DEVICE -> HOST (numpy) -> HOST_COMPRESSED
  (columnar RLE/dict/byte-codec blocks — ``codec.py``), mirroring the
  reference's MEMORY_ONLY -> MEMORY_AND_DISK ladder with the host RAM
  playing the disk role (HBM:host ~ heap:disk in bandwidth ratio).
- eviction is LRU over cached entries.  Demotion is safe mid-query: a
  reader holds a reference to the decompressed/materialized batch it got
  from ``get``, so the entry's storage can change underneath it freely.

Single-controller scope: accounting covers this process's session (the
reference's per-executor MemoryManager scope; multi-host counterparts
each run their own).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import codec as codec_mod
from . import config as C
from .columnar import ColumnBatch, ColumnVector

HBM_BUDGET = C.conf("spark.tpu.memory.hbmBudget").doc(
    "Device HBM budget in bytes for execution+storage accounting; 0 = "
    "discover from device memory_stats (fallback 16 GiB)."
).int(0)

STORAGE_FRACTION = C.conf("spark.tpu.memory.storageFraction").doc(
    "Fraction of the HBM budget protected for the device cache before "
    "execution reservations may force eviction (UnifiedMemoryManager's "
    "spark.memory.storageFraction analog)."
).float(0.3)

CACHE_CODEC = C.conf("spark.tpu.cache.codec").doc(
    "Byte codec for HOST_COMPRESSED cache blocks: one of codec.CODECS "
    "(zlib/lzma/bz2 always; lz4/zstd when their wheels are present)."
).string("zlib")

HOST_BUDGET = C.conf("spark.tpu.memory.hostBudget").doc(
    "Host-RAM budget in bytes for the shuffle path's exchange staging "
    "(bucketed map output, fetched blocks, drained shards); 0 = discover "
    "physical RAM via psutil or os.sysconf (fallback 16 GiB).  Sides "
    "that cannot reserve spill to disk instead of growing unbounded."
).check(lambda v: v >= 0).int(0)


class HBMOutOfMemoryError(MemoryError):
    """Execution reservation cannot fit even after evicting all unpinned
    storage (SparkOutOfMemoryError analog)."""


class HostMemoryError(MemoryError):
    """Host-RAM staging can proceed NEITHER in memory nor via spill
    (disk error, or the ledger exhausted by concurrent reservers): the
    query fails bounded with the reserver and exchange named, never
    partial results (the spill ladder's SparkOutOfMemoryError rung)."""

    def __init__(self, owner: str, requested: int, budget: int,
                 holders: Optional[Dict[str, int]] = None,
                 exchange: str = "", detail: str = ""):
        self.owner = owner
        self.requested = requested
        self.budget = budget
        self.holders = dict(holders or {})
        self.exchange = exchange
        self.detail = detail
        held = sum(self.holders.values())
        msg = (f"{owner}: cannot stage {requested} B"
               f"{' for exchange ' + exchange if exchange else ''} "
               f"(host budget {budget} B, held {held} B by "
               f"{len(self.holders)} reserver(s))")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class HostMemoryPressure(HostMemoryError):
    """A ledger reservation failed at a point where a DEGRADED mode can
    still complete the query (the drained post-exchange shard of a
    distributed join, which the crossproc grace path can re-bucket to
    disk and join piecewise).  Raisers guarantee the underlying state is
    intact and re-consumable; callers with no grace path installed may
    treat it exactly as its ``HostMemoryError`` base — bounded, never
    partial."""


def batch_nbytes(batch: ColumnBatch) -> int:
    from .columnar import unmaterialized_runs
    total = 0
    for v in batch.vectors:
        runs = unmaterialized_runs(v)
        if runs is not None:
            # lazy run vector: the ledger charges what is actually held
            # (run values + int64 lengths), not the inflated row count
            total += int(np.asarray(runs.run_values).nbytes
                         + np.asarray(runs.run_lengths).nbytes)
        else:
            total += np.dtype(v.dtype.np_dtype).itemsize * batch.capacity
        if v.valid is not None:
            total += batch.capacity
    if batch.row_valid is not None:
        total += batch.capacity
    return total


def _device_budget(conf) -> int:
    fixed = conf.get(HBM_BUDGET)
    if fixed:
        return fixed
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return 16 << 30


class MemoryManager:
    """Execution/storage split over one HBM budget with storage eviction."""

    def __init__(self, conf):
        self._conf = conf
        self._lock = threading.RLock()
        self.budget = _device_budget(conf)
        self.storage_floor = int(self.budget *
                                 conf.get(STORAGE_FRACTION))
        self._execution: Dict[str, int] = {}
        self._storage: Dict[str, int] = {}
        self._evict_cb = None            # set by the cache manager

    # -- introspection ------------------------------------------------------
    @property
    def execution_used(self) -> int:
        return sum(self._execution.values())

    @property
    def storage_used(self) -> int:
        return sum(self._storage.values())

    @property
    def free(self) -> int:
        return self.budget - self.execution_used - self.storage_used

    def set_eviction_callback(self, cb) -> None:
        """cb(nbytes_needed) -> bytes actually released."""
        self._evict_cb = cb

    # -- execution pool -----------------------------------------------------
    def acquire_execution(self, owner: str, nbytes: int) -> None:
        with self._lock:
            if nbytes > self.free and self._evict_cb is not None:
                # evict storage above the protected floor
                evictable = max(0, self.storage_used - self.storage_floor)
                want = min(nbytes - self.free, evictable)
                if want > 0:
                    self._evict_cb(want)
            if nbytes > self.free:
                raise HBMOutOfMemoryError(
                    f"{owner}: need {nbytes} B, free {self.free} B of "
                    f"{self.budget} B (execution {self.execution_used} B, "
                    f"storage {self.storage_used} B)")
            self._execution[owner] = self._execution.get(owner, 0) + nbytes

    def release_execution(self, owner: str) -> None:
        with self._lock:
            self._execution.pop(owner, None)

    def execution_held(self, owner: str) -> int:
        """Bytes an owner still holds (0 = clean) — the post-task leak
        check's locked accessor."""
        with self._lock:
            return self._execution.get(owner, 0)

    # -- storage pool -------------------------------------------------------
    def try_acquire_storage(self, key: str, nbytes: int) -> bool:
        with self._lock:
            if nbytes > self.free and self._evict_cb is not None:
                self._evict_cb(nbytes - self.free)
            if nbytes > self.free:
                return False
            self._storage[key] = self._storage.get(key, 0) + nbytes
            return True

    def release_storage(self, key: str) -> None:
        with self._lock:
            self._storage.pop(key, None)


def discover_host_budget() -> int:
    """Physical host RAM in bytes: psutil when its wheel is present, else
    ``os.sysconf`` (absent on some platforms), else a 16 GiB guess."""
    try:
        import psutil
        return int(psutil.virtual_memory().total)
    except Exception:
        pass
    try:
        return int(os.sysconf("SC_PAGE_SIZE")) * int(os.sysconf("SC_PHYS_PAGES"))
    except Exception:
        pass
    return 16 << 30


class HostMemoryLedger:
    """Owner-keyed host-RAM reservations for the shuffle staging path.

    The host twin of ``MemoryManager``'s execution pool, minus eviction:
    there is no storage to demote, so over-budget reservers either spill
    (``try_reserve`` returns False) or fail structured (``reserve``
    raises ``HostMemoryError``).  ``peak`` records the high-water mark of
    accounted bytes for the peak_host_bytes gauge."""

    def __init__(self, conf=None, budget: Optional[int] = None):
        if budget is None:
            fixed = conf.get(HOST_BUDGET) if conf is not None else 0
            budget = fixed or discover_host_budget()
        self.budget = int(budget)
        self._lock = threading.Lock()
        self._held: Dict[str, int] = {}
        self.peak = 0

    @property
    def used(self) -> int:
        with self._lock:
            return sum(self._held.values())

    @property
    def free(self) -> int:
        return self.budget - self.used

    def held(self, owner: str) -> int:
        with self._lock:
            return self._held.get(owner, 0)

    def owners(self) -> List[str]:
        """Snapshot of every owner currently holding a reservation (the
        analysis ledger-scope check diffs this across a query)."""
        with self._lock:
            return list(self._held)

    def try_reserve(self, owner: str, nbytes: int) -> bool:
        nbytes = int(nbytes)
        with self._lock:
            used = sum(self._held.values())
            if used + nbytes > self.budget:
                return False
            self._held[owner] = self._held.get(owner, 0) + nbytes
            self.peak = max(self.peak, used + nbytes)
            return True

    def reserve(self, owner: str, nbytes: int, exchange: str = "") -> None:
        if not self.try_reserve(owner, nbytes):
            with self._lock:
                holders = dict(self._held)
            raise HostMemoryError(owner, int(nbytes), self.budget,
                                  holders=holders, exchange=exchange)

    def release(self, owner: str, nbytes: Optional[int] = None) -> None:
        with self._lock:
            if nbytes is None:
                self._held.pop(owner, None)
                return
            left = self._held.get(owner, 0) - int(nbytes)
            if left > 0:
                self._held[owner] = left
            else:
                self._held.pop(owner, None)

    def release_prefix(self, prefix: str) -> int:
        """Drop every reservation whose owner starts with ``prefix`` —
        the query-exit safety net against leaks on error paths, and the
        epoch-abort sweep lineage recovery runs BEFORE re-executing a
        statement (a dead epoch's map staging must not shrink the
        re-run's budget).  Returns the number of bytes freed so callers
        can account the sweep (0 = nothing was held under the scope)."""
        freed = 0
        with self._lock:
            for owner in [o for o in self._held if o.startswith(prefix)]:
                freed += self._held.pop(owner)
        return freed


# ---------------------------------------------------------------------------
# storage levels & cached entries
# ---------------------------------------------------------------------------

class StorageLevel:
    DEVICE = "DEVICE"                      # HBM-resident (MEMORY_ONLY)
    HOST = "HOST"                          # numpy (MEMORY_AND_DISK's disk)
    HOST_COMPRESSED = "HOST_COMPRESSED"    # codec blocks (compressed cache)


class _Entry:
    __slots__ = ("key", "level", "requested", "batch", "blocks", "nbytes",
                 "last_used", "uid")

    def __init__(self, key, level, requested, batch, nbytes):
        self.key = key
        self.level = level
        self.requested = requested
        self.batch = batch            # device or host ColumnBatch
        self.blocks = None            # HOST_COMPRESSED payload
        self.nbytes = nbytes
        self.last_used = time.monotonic()
        self.uid = None               # stable plan-key identity (see get())


def _compress_batch(batch: ColumnBatch, codec_name: str):
    host = batch.to_host()
    cols = []
    for v in host.vectors:
        enc = codec_mod.encode_column(np.asarray(v.data), codec_name)
        validity = (None if v.valid is None
                    else np.packbits(np.asarray(v.valid, bool)))
        cols.append((enc, validity, v.dtype, v.dictionary))
    rv = (None if host.row_valid is None
          else np.packbits(np.asarray(host.row_valid, bool)))
    return (host.names, cols, rv, host.capacity)


def _decompress_batch(blocks) -> ColumnBatch:
    names, cols, rv, capacity = blocks
    vectors = []
    for enc, validity, dt, dictionary in cols:
        data = codec_mod.decode_column(enc)
        valid = (None if validity is None
                 else np.unpackbits(validity)[:capacity].astype(bool))
        vectors.append(ColumnVector(data, dt, valid, dictionary))
    row_valid = (None if rv is None
                 else np.unpackbits(rv)[:capacity].astype(bool))
    return ColumnBatch(names, vectors, row_valid, capacity)


class DeviceCacheManager:
    """Plan-keyed cached relations with demotion + LRU eviction."""

    def __init__(self, memory: MemoryManager, conf):
        self._memory = memory
        self._conf = conf
        self._entries: Dict[str, _Entry] = {}
        # ONE lock with the memory manager: the eviction callback runs
        # under it, and a second lock here would order-invert (cache.put ->
        # memory.try_acquire_storage vs memory.acquire_execution -> _evict)
        self._lock = memory._lock
        memory.set_eviction_callback(self._evict)

    # -- public -------------------------------------------------------------
    def put(self, key: str, batch: ColumnBatch,
            level: str = StorageLevel.DEVICE) -> None:
        if level not in (StorageLevel.DEVICE, StorageLevel.HOST,
                         StorageLevel.HOST_COMPRESSED):
            raise ValueError(
                f"unknown storage level {level!r}; expected one of "
                f"StorageLevel.DEVICE/HOST/HOST_COMPRESSED")
        nbytes = batch_nbytes(batch)
        with self._lock:
            self.remove(key)
            entry = _Entry(key, level, level, batch, nbytes)
            from .sql.logical import _batch_uid
            entry.uid = _batch_uid(batch)
            if level == StorageLevel.DEVICE:
                if self._memory.try_acquire_storage(key, nbytes):
                    entry.batch = batch.to_device()
                else:                      # no room: demote on entry
                    entry.level = StorageLevel.HOST
                    entry.batch = batch.to_host()
            elif level == StorageLevel.HOST:
                entry.batch = batch.to_host()
            else:
                entry.blocks = _compress_batch(
                    batch, self._conf.get(CACHE_CODEC))
                entry.batch = None
                entry.nbytes = sum(c[0].nbytes for c in entry.blocks[1])
            self._entries[key] = entry

    def get(self, key: str) -> Optional[ColumnBatch]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            entry.last_used = time.monotonic()
            if entry.level == StorageLevel.HOST_COMPRESSED:
                if entry.batch is None:       # decompress ONCE; keep the
                    entry.batch = _decompress_batch(entry.blocks)  # host copy
                batch = entry.batch
            else:
                batch = entry.batch
            # promote back toward the requested level opportunistically —
            # BOTH for decompressed blocks and for entries that were put()
            # straight to HOST because HBM was full at the time
            if entry.level != StorageLevel.DEVICE \
                    and entry.requested == StorageLevel.DEVICE \
                    and self._memory.try_acquire_storage(
                        key, batch_nbytes(batch)):
                entry.batch = batch.to_device()
                entry.blocks = None
                entry.level = StorageLevel.DEVICE
                entry.nbytes = batch_nbytes(batch)
                batch = entry.batch
            # every object served under this key carries the SAME uid, so
            # plan keys built over a cached batch (cache-on-cache) stay
            # stable across demote/decompress/promote cycles
            if entry.uid is not None:
                try:
                    batch._cache_uid = entry.uid
                except Exception:
                    pass
            return batch

    def remove(self, key: str) -> bool:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return False
            if entry.level == StorageLevel.DEVICE:
                self._memory.release_storage(key)
            return True

    def clear(self) -> None:
        with self._lock:
            for key in list(self._entries):
                self.remove(key)

    def entries(self) -> List[dict]:
        with self._lock:
            return [{"key": e.key, "level": e.level, "nbytes": e.nbytes}
                    for e in self._entries.values()]

    # -- eviction (called under memory pressure) ----------------------------
    def _evict(self, nbytes_needed: int) -> int:
        """Demote least-recently-used DEVICE entries to HOST_COMPRESSED
        until ``nbytes_needed`` device bytes are free."""
        released = 0
        with self._lock:
            device_entries = sorted(
                (e for e in self._entries.values()
                 if e.level == StorageLevel.DEVICE),
                key=lambda e: e.last_used)
            for entry in device_entries:
                if released >= nbytes_needed:
                    break
                host = entry.batch.to_host()
                entry.blocks = _compress_batch(
                    host, self._conf.get(CACHE_CODEC))
                entry.batch = None        # dropped to free host refs too;
                entry.level = StorageLevel.HOST_COMPRESSED  # get() re-caches
                self._memory.release_storage(entry.key)
                released += entry.nbytes
                entry.nbytes = sum(c[0].nbytes for c in entry.blocks[1])
        return released
