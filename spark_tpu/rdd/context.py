"""SparkContext: the core entry point (`core/SparkContext.scala` analog).

One process = driver + executors (the SPMD mesh replaces the task-scheduler
split for device work), so the context is a thin service registry:
parallelize/textFile build RDD lineages, broadcast/accumulator mirror the
reference APIs (`broadcast/TorrentBroadcast.scala:57`,
`util/AccumulatorV2.scala`), and the event bus + job bookkeeping live in
`spark_tpu.events`.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Any, Callable, Iterable, List, Optional

from .rdd import RDD

__all__ = ["SparkContext", "Broadcast", "Accumulator", "AccumulatorParam"]


class Broadcast:
    """Read-only value shared with all tasks.  In-process there is exactly
    one copy by construction — the torrent machinery's job (chunked
    BlockManager distribution) only exists across hosts, where the device
    path uses replication/all_gather instead."""

    _next_id = itertools.count()

    def __init__(self, value):
        self._value = value
        self.id = next(self._next_id)
        self._destroyed = False

    @property
    def value(self):
        if self._destroyed:
            raise RuntimeError(f"broadcast {self.id} was destroyed")
        return self._value

    def unpersist(self, blocking: bool = False) -> None:
        pass

    def destroy(self) -> None:
        self._destroyed = True
        self._value = None


class AccumulatorParam:
    def zero(self, value):
        return 0

    def addInPlace(self, a, b):
        return a + b


class Accumulator:
    """Write-only-from-tasks counter (`AccumulatorV2`); thread-safe."""

    _next_id = itertools.count()

    def __init__(self, value, param: Optional[AccumulatorParam] = None):
        self._value = value
        self._param = param or AccumulatorParam()
        self._lock = threading.Lock()
        self.id = next(self._next_id)

    def add(self, term) -> None:
        with self._lock:
            self._value = self._param.addInPlace(self._value, term)

    def __iadd__(self, term):
        self.add(term)
        return self

    @property
    def value(self):
        return self._value

    @value.setter
    def value(self, v):
        self._value = v

    def __repr__(self):
        return f"Accumulator(id={self.id}, value={self._value})"


class SparkContext:
    """Driver-side root object.  ``master`` accepts `local[*]`-style URLs
    for API parity; in-slice parallelism actually comes from the device mesh
    (`spark_tpu.parallel.mesh`), not process placement."""

    _active: Optional["SparkContext"] = None

    def __init__(self, master: str = "local[*]", appName: str = "spark-tpu",
                 conf=None, session=None):
        from .. import config as C
        self.master = master
        self.appName = appName
        self._conf = conf if conf is not None else C.Conf()
        self._rdd_ids = itertools.count()
        self._default_parallelism = self._parse_parallelism(master)
        self.startTime = int(time.time() * 1000)
        self._stopped = False
        self._session_ref = session
        SparkContext._active = self

    @classmethod
    def getOrCreate(cls, master: str = "local[*]",
                    appName: str = "spark-tpu") -> "SparkContext":
        if cls._active is not None and not cls._active._stopped:
            return cls._active
        return cls(master, appName)

    def _parse_parallelism(self, master: str) -> int:
        if master.startswith("local["):
            inner = master[len("local["):-1]
            if inner == "*":
                return os.cpu_count() or 4
            return int(inner)
        return os.cpu_count() or 4

    @property
    def defaultParallelism(self) -> int:
        return self._default_parallelism

    def _next_rdd_id(self) -> int:
        return next(self._rdd_ids)

    def _session(self):
        if self._session_ref is None:
            from ..sql.session import SparkSession
            self._session_ref = SparkSession.builder.getOrCreate()
        return self._session_ref

    # -- RDD constructors --------------------------------------------------
    def parallelize(self, data: Iterable[Any],
                    numSlices: Optional[int] = None) -> RDD:
        items = list(data)
        n = numSlices or min(self._default_parallelism, max(1, len(items)))
        n = max(1, n)
        # contiguous slices, Spark's ParallelCollectionRDD.slice semantics
        slices: List[List[Any]] = []
        for i in range(n):
            start = (i * len(items)) // n
            end = ((i + 1) * len(items)) // n
            slices.append(items[start:end])
        return RDD(self, n, lambda i: slices[i], name="ParallelCollectionRDD")

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              numSlices: Optional[int] = None) -> RDD:
        if end is None:
            start, end = 0, start
        return self.parallelize(range(start, end, step), numSlices)

    def emptyRDD(self) -> RDD:
        return self.parallelize([], 1)

    def textFile(self, path: str,
                 minPartitions: Optional[int] = None) -> RDD:
        from ..io import _resolve_paths
        files = _resolve_paths(path)
        lines: List[str] = []
        for f in files:
            with open(f, "r", encoding="utf-8") as fh:
                lines += [ln.rstrip("\n") for ln in fh]
        return self.parallelize(lines, minPartitions)

    def wholeTextFiles(self, path: str) -> RDD:
        from ..io import _resolve_paths
        files = _resolve_paths(path)
        out = []
        for f in files:
            with open(f, "r", encoding="utf-8") as fh:
                out.append((f, fh.read()))
        return self.parallelize(out, len(out) or 1)

    def union(self, rdds: List[RDD]) -> RDD:
        out = rdds[0]
        for r in rdds[1:]:
            out = out.union(r)
        return out

    # -- shared variables --------------------------------------------------
    def broadcast(self, value) -> Broadcast:
        return Broadcast(value)

    def accumulator(self, value, param: Optional[AccumulatorParam] = None
                    ) -> Accumulator:
        return Accumulator(value, param)

    # -- job control -------------------------------------------------------
    def runJob(self, rdd: RDD, func: Callable, partitions=None) -> List[Any]:
        parts = partitions if partitions is not None \
            else range(rdd.getNumPartitions())
        return [func(iter(rdd._partition(i))) for i in parts]

    def setJobGroup(self, groupId: str, description: str) -> None:
        self._job_group = (groupId, description)

    def setLogLevel(self, level: str) -> None:
        import logging
        logging.getLogger("spark_tpu").setLevel(level.upper())

    def stop(self) -> None:
        self._stopped = True
        if SparkContext._active is self:
            SparkContext._active = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __repr__(self):
        return f"SparkContext(master={self.master}, appName={self.appName})"
