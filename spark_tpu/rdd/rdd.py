"""RDD: the resilient-distributed-dataset API (`core/rdd/RDD.scala:76` +
`PairRDDFunctions.scala` analog).

Semantics mirror the reference: lazy transformations building a lineage
graph, actions that execute it, hash-partitioned shuffles for the ByKey
family, and the same operation surface (map:369.., reduceByKey, cogroup,
treeAggregate:1125, ...).

Execution model: partitions are host Python lists evaluated through the
lineage chain (one "task" per partition).  On TPU hardware the RDD API is
the control-plane/compat layer — columnar DataFrames are the accelerated
path — mirroring how PySpark RDDs pay the pickle pipe while DataFrames stay
in Tungsten (`python/pyspark/rdd.py` vs `sql/dataframe.py`).  Numeric RDDs
can hop to the device path via ``toDF``.
"""

from __future__ import annotations

import bisect
import heapq
import itertools
import math
import os
import random
from collections import defaultdict
from functools import reduce as _freduce
from typing import Any, Callable, Iterable, List, Optional, Tuple

__all__ = ["RDD", "Partitioner", "HashPartitioner", "StatCounter"]


def _portable_hash(x) -> int:
    """Deterministic hash for shuffle partitioning (tuples/None like Spark's
    portable_hash; python hash randomization must not leak into layouts)."""
    if x is None:
        return 0
    if isinstance(x, bool):
        return int(x)
    if isinstance(x, int):
        return x
    if isinstance(x, str):
        h = 0
        for ch in x:
            h = (31 * h + ord(ch)) & 0xFFFFFFFF
        return h
    if isinstance(x, float):
        return hash(x)
    if isinstance(x, tuple):
        h = 0x345678
        for item in x:
            h = (h * 31 + _portable_hash(item)) & 0xFFFFFFFF
        return h
    return hash(x)


class Partitioner:
    def __init__(self, num_partitions: int):
        self.numPartitions = num_partitions

    def __call__(self, key) -> int:
        raise NotImplementedError

    def __eq__(self, other):
        return (type(self) is type(other)
                and self.numPartitions == other.numPartitions)


class HashPartitioner(Partitioner):
    """`Partitioner.scala:80` HashPartitioner."""

    def __call__(self, key) -> int:
        return _portable_hash(key) % self.numPartitions


class StatCounter:
    """`util/StatCounter.scala`: running count/mean/variance/min/max."""

    def __init__(self, values: Iterable[float] = ()):
        self.n = 0
        self.mu = 0.0
        self.m2 = 0.0
        self.maxValue = -math.inf
        self.minValue = math.inf
        for v in values:
            self.merge(v)

    def merge(self, v: float) -> "StatCounter":
        self.n += 1
        delta = v - self.mu
        self.mu += delta / self.n
        self.m2 += delta * (v - self.mu)
        self.maxValue = max(self.maxValue, v)
        self.minValue = min(self.minValue, v)
        return self

    def mergeStats(self, o: "StatCounter") -> "StatCounter":
        if o.n == 0:
            return self
        if self.n == 0:
            self.n, self.mu, self.m2 = o.n, o.mu, o.m2
            self.maxValue, self.minValue = o.maxValue, o.minValue
            return self
        delta = o.mu - self.mu
        total = self.n + o.n
        self.mu = (self.mu * self.n + o.mu * o.n) / total
        self.m2 += o.m2 + delta * delta * self.n * o.n / total
        self.n = total
        self.maxValue = max(self.maxValue, o.maxValue)
        self.minValue = min(self.minValue, o.minValue)
        return self

    def count(self):
        return self.n

    def mean(self):
        return self.mu

    def sum(self):
        return self.mu * self.n

    def variance(self):
        return self.m2 / self.n if self.n else math.nan

    def sampleVariance(self):
        return self.m2 / (self.n - 1) if self.n > 1 else math.nan

    def stdev(self):
        return math.sqrt(self.variance())

    def sampleStdev(self):
        return math.sqrt(self.sampleVariance())

    def min(self):  # noqa: A003
        return self.minValue

    def max(self):  # noqa: A003
        return self.maxValue

    def __repr__(self):
        return (f"(count: {self.n}, mean: {self.mu}, stdev: {self.stdev()}, "
                f"max: {self.maxValue}, min: {self.minValue})")


class RDD:
    """Lazy lineage node: ``_compute(split)`` yields one partition's rows."""

    def __init__(self, sc, num_partitions: int,
                 compute: Callable[[int], Iterable[Any]],
                 parents: Tuple["RDD", ...] = (),
                 partitioner: Optional[Partitioner] = None,
                 name: str = "RDD"):
        self._sc = sc
        self._num = num_partitions
        self._compute_fn = compute
        self._parents = parents
        self.partitioner = partitioner
        self._name = name
        self._cache: Optional[List[List[Any]]] = None
        self.id = sc._next_rdd_id()

    # -- plumbing ---------------------------------------------------------
    def getNumPartitions(self) -> int:
        return self._num

    def _partition(self, i: int) -> List[Any]:
        if self._cache is not None:
            return self._cache[i]
        return list(self._compute_fn(i))

    def _materialize(self) -> List[List[Any]]:
        return [self._partition(i) for i in range(self._num)]

    def cache(self) -> "RDD":
        return self.persist()

    def persist(self, storageLevel=None) -> "RDD":
        if self._cache is None:
            self._cache = self._materialize()
        return self

    def unpersist(self) -> "RDD":
        self._cache = None
        return self

    def checkpoint(self) -> None:
        self.persist()

    def setName(self, name: str) -> "RDD":
        self._name = name
        return self

    def name(self):
        return self._name

    def toDebugString(self) -> str:
        lines = []

        def walk(r, depth):
            lines.append("  " * depth + f"({r.getNumPartitions()}) "
                         f"{r._name} [{r.id}]")
            for p in r._parents:
                walk(p, depth + 1)
        walk(self, 0)
        return "\n".join(lines)

    def _derive(self, fn, num=None, partitioner=None, name="RDD") -> "RDD":
        return RDD(self._sc, num if num is not None else self._num, fn,
                   parents=(self,), partitioner=partitioner, name=name)

    # -- transformations (narrow) ----------------------------------------
    def map(self, f) -> "RDD":
        return self._derive(lambda i: (f(x) for x in self._partition(i)),
                            name="MapRDD")

    def flatMap(self, f) -> "RDD":
        return self._derive(
            lambda i: itertools.chain.from_iterable(
                f(x) for x in self._partition(i)), name="FlatMapRDD")

    def filter(self, f) -> "RDD":
        return self._derive(lambda i: (x for x in self._partition(i) if f(x)),
                            partitioner=self.partitioner, name="FilterRDD")

    def mapPartitions(self, f, preservesPartitioning=False) -> "RDD":
        return self._derive(
            lambda i: f(iter(self._partition(i))),
            partitioner=self.partitioner if preservesPartitioning else None,
            name="MapPartitionsRDD")

    def mapPartitionsWithIndex(self, f, preservesPartitioning=False) -> "RDD":
        return self._derive(
            lambda i: f(i, iter(self._partition(i))),
            partitioner=self.partitioner if preservesPartitioning else None,
            name="MapPartitionsRDD")

    def glom(self) -> "RDD":
        return self._derive(lambda i: [self._partition(i)], name="GlomRDD")

    def zipWithIndex(self) -> "RDD":
        sizes = [len(self._partition(i)) for i in range(self._num)]
        starts = [0]
        for s in sizes[:-1]:
            starts.append(starts[-1] + s)

        def fn(i):
            return ((x, starts[i] + j)
                    for j, x in enumerate(self._partition(i)))
        return self._derive(fn, name="ZipWithIndexRDD")

    def zip(self, other: "RDD") -> "RDD":
        if self._num != other._num:
            raise ValueError("can only zip RDDs with the same number of partitions")

        def fn(i):
            a, b = self._partition(i), other._partition(i)
            if len(a) != len(b):
                raise ValueError("can only zip RDDs with equal partition sizes")
            return zip(a, b)
        return RDD(self._sc, self._num, fn, parents=(self, other),
                   name="ZippedRDD")

    def keyBy(self, f) -> "RDD":
        return self.map(lambda x: (f(x), x))

    def sample(self, withReplacement: bool, fraction: float,
               seed: Optional[int] = None) -> "RDD":
        seed = seed if seed is not None else random.randrange(1 << 30)

        def fn(i):
            rng = random.Random(seed + i)
            for x in self._partition(i):
                if withReplacement:
                    for _ in range(_poisson(rng, fraction)):
                        yield x
                elif rng.random() < fraction:
                    yield x
        return self._derive(fn, name="SampledRDD")

    def union(self, other: "RDD") -> "RDD":
        n_self = self._num

        def fn(i):
            if i < n_self:
                return self._partition(i)
            return other._partition(i - n_self)
        return RDD(self._sc, self._num + other._num, fn,
                   parents=(self, other), name="UnionRDD")

    def cartesian(self, other: "RDD") -> "RDD":
        def fn(i):
            a, b = divmod(i, other._num)
            return ((x, y) for x in self._partition(a)
                    for y in other._partition(b))
        return RDD(self._sc, self._num * other._num, fn,
                   parents=(self, other), name="CartesianRDD")

    def distinct(self, numPartitions: Optional[int] = None) -> "RDD":
        return (self.map(lambda x: (x, None))
                .reduceByKey(lambda a, b: a, numPartitions)
                .map(lambda kv: kv[0]))

    def intersection(self, other: "RDD") -> "RDD":
        return (self.map(lambda x: (x, 1)).cogroup(
            other.map(lambda x: (x, 1)))
            .filter(lambda kv: len(kv[1][0]) > 0 and len(kv[1][1]) > 0)
            .map(lambda kv: kv[0]))

    def subtract(self, other: "RDD") -> "RDD":
        return (self.map(lambda x: (x, x))
                .cogroup(other.map(lambda x: (x, 1)))
                .flatMap(lambda kv: kv[1][0] if len(kv[1][1]) == 0 else []))

    def groupBy(self, f, numPartitions: Optional[int] = None) -> "RDD":
        return self.map(lambda x: (f(x), x)).groupByKey(numPartitions)

    def sortBy(self, keyfunc, ascending: bool = True,
               numPartitions: Optional[int] = None) -> "RDD":
        return (self.keyBy(keyfunc)
                .sortByKey(ascending, numPartitions)
                .map(lambda kv: kv[1]))

    def repartition(self, numPartitions: int) -> "RDD":
        return self.coalesce(numPartitions, shuffle=True)

    def coalesce(self, numPartitions: int, shuffle: bool = False) -> "RDD":
        if shuffle:
            counter = itertools.count()

            def spread(i):
                return (((next(counter) + i) % numPartitions, x)
                        for x in self._partition(i))
            keyed = self._derive(spread, name="CoalesceKeyed")
            return keyed._shuffle(numPartitions).mapPartitions(
                lambda it: (v for _, v in it))
        numPartitions = min(numPartitions, self._num)
        groups = [[] for _ in range(numPartitions)]
        for i in range(self._num):
            groups[i % numPartitions].append(i)

        def fn(i):
            return itertools.chain.from_iterable(
                self._partition(j) for j in groups[i])
        return self._derive(fn, num=numPartitions, name="CoalescedRDD")

    def pipe(self, command: str) -> "RDD":
        import subprocess

        def fn(i):
            inp = "\n".join(str(x) for x in self._partition(i))
            out = subprocess.run(command, input=inp, capture_output=True,
                                 shell=True, text=True, check=True)
            return (ln for ln in out.stdout.splitlines())
        return self._derive(fn, name="PipedRDD")

    # -- pair transformations (shuffles) ----------------------------------
    def _shuffle(self, numPartitions: Optional[int] = None,
                 partitioner: Optional[Partitioner] = None) -> "RDD":
        """Hash-exchange (k, v) rows (ShuffledRDD; one file per reducer in
        the reference's BypassMergeSortShuffleWriter sense)."""
        part = partitioner or HashPartitioner(numPartitions or self._num)
        buckets: Optional[List[List[Any]]] = None

        def materialize():
            nonlocal buckets
            if buckets is None:
                buckets = [[] for _ in range(part.numPartitions)]
                for i in range(self._num):
                    for kv in self._partition(i):
                        buckets[part(kv[0])].append(kv)
            return buckets

        def fn(i):
            return materialize()[i]
        return self._derive(fn, num=part.numPartitions, partitioner=part,
                            name="ShuffledRDD")

    def partitionBy(self, numPartitions: int,
                    partitionFunc=None) -> "RDD":
        part = HashPartitioner(numPartitions)
        if partitionFunc is not None:
            class _F(Partitioner):
                def __call__(self, key):
                    return partitionFunc(key) % self.numPartitions
            part = _F(numPartitions)
        return self._shuffle(partitioner=part)

    def combineByKey(self, createCombiner, mergeValue, mergeCombiners,
                     numPartitions: Optional[int] = None) -> "RDD":
        """`PairRDDFunctions.combineByKeyWithClassTag` — map-side combine
        then reduce-side merge."""
        def map_side(i):
            acc = {}
            for k, v in self._partition(i):
                if k in acc:
                    acc[k] = mergeValue(acc[k], v)
                else:
                    acc[k] = createCombiner(v)
            return acc.items()
        combined = self._derive(map_side, name="MapSideCombine")
        shuffled = combined._shuffle(numPartitions)

        def reduce_side(i):
            acc = {}
            for k, c in shuffled._partition(i):
                if k in acc:
                    acc[k] = mergeCombiners(acc[k], c)
                else:
                    acc[k] = c
            return acc.items()
        return shuffled._derive(reduce_side, partitioner=shuffled.partitioner,
                                name="CombineByKeyRDD")

    def reduceByKey(self, func, numPartitions: Optional[int] = None) -> "RDD":
        return self.combineByKey(lambda v: v, func, func, numPartitions)

    def foldByKey(self, zeroValue, func,
                  numPartitions: Optional[int] = None) -> "RDD":
        return self.combineByKey(lambda v: func(zeroValue, v), func, func,
                                 numPartitions)

    def aggregateByKey(self, zeroValue, seqFunc, combFunc,
                       numPartitions: Optional[int] = None) -> "RDD":
        return self.combineByKey(lambda v: seqFunc(zeroValue, v), seqFunc,
                                 combFunc, numPartitions)

    def groupByKey(self, numPartitions: Optional[int] = None) -> "RDD":
        return self.combineByKey(lambda v: [v],
                                 lambda c, v: c + [v],
                                 lambda a, b: a + b, numPartitions)

    def mapValues(self, f) -> "RDD":
        return self._derive(
            lambda i: ((k, f(v)) for k, v in self._partition(i)),
            partitioner=self.partitioner, name="MapValuesRDD")

    def flatMapValues(self, f) -> "RDD":
        return self._derive(
            lambda i: ((k, w) for k, v in self._partition(i) for w in f(v)),
            partitioner=self.partitioner, name="FlatMapValuesRDD")

    def keys(self) -> "RDD":
        return self.map(lambda kv: kv[0])

    def values(self) -> "RDD":
        return self.map(lambda kv: kv[1])

    def cogroup(self, other: "RDD",
                numPartitions: Optional[int] = None) -> "RDD":
        num = numPartitions or max(self._num, other._num)
        part = HashPartitioner(num)
        left = self._shuffle(partitioner=part)
        right = other._shuffle(partitioner=part)

        def fn(i):
            a, b = defaultdict(list), defaultdict(list)
            for k, v in left._partition(i):
                a[k].append(v)
            for k, v in right._partition(i):
                b[k].append(v)
            for k in {**a, **b}:
                yield (k, (a.get(k, []), b.get(k, [])))
        return RDD(self._sc, num, fn, parents=(left, right),
                   partitioner=part, name="CoGroupedRDD")

    def join(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in kv[1][0] for b in kv[1][1]))

    def leftOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in kv[1][0]
                        for b in (kv[1][1] or [None])))

    def rightOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in (kv[1][0] or [None])
                        for b in kv[1][1]))

    def fullOuterJoin(self, other: "RDD", numPartitions=None) -> "RDD":
        return self.cogroup(other, numPartitions).flatMap(
            lambda kv: ((kv[0], (a, b)) for a in (kv[1][0] or [None])
                        for b in (kv[1][1] or [None])))

    def sortByKey(self, ascending: bool = True,
                  numPartitions: Optional[int] = None) -> "RDD":
        """Range-partitioned global sort (`Partitioner.scala:108`
        RangePartitioner: sampled splitters → exchange → local sort)."""
        num = numPartitions or self._num
        all_keys = [kv[0] for i in range(self._num)
                    for kv in self._partition(i)]
        if not all_keys:
            return self
        rng = random.Random(17)
        sample = sorted(rng.sample(all_keys, min(len(all_keys), 20 * num)))
        splitters = [sample[int(len(sample) * (i + 1) / num)]
                     for i in range(num - 1)] if num > 1 else []

        class _Range(Partitioner):
            def __call__(self, key):
                idx = bisect.bisect_left(splitters, key)
                return idx if ascending else self.numPartitions - 1 - idx

        shuffled = self._shuffle(partitioner=_Range(num))
        return shuffled._derive(
            lambda i: iter(sorted(shuffled._partition(i),
                                  key=lambda kv: kv[0],
                                  reverse=not ascending)),
            partitioner=shuffled.partitioner, name="SortedRDD")

    # -- actions ----------------------------------------------------------
    def collect(self) -> List[Any]:
        out: List[Any] = []
        for i in range(self._num):
            out += list(self._partition(i))
        return out

    def collectAsMap(self) -> dict:
        return dict(self.collect())

    def count(self) -> int:
        return sum(len(list(self._partition(i))) for i in range(self._num))

    def countByKey(self) -> dict:
        out: dict = defaultdict(int)
        for k, _ in self.collect():
            out[k] += 1
        return dict(out)

    def countByValue(self) -> dict:
        out: dict = defaultdict(int)
        for x in self.collect():
            out[x] += 1
        return dict(out)

    def first(self):
        for i in range(self._num):
            p = list(self._partition(i))
            if p:
                return p[0]
        raise ValueError("RDD is empty")

    def take(self, n: int) -> List[Any]:
        out: List[Any] = []
        for i in range(self._num):
            if len(out) >= n:
                break
            out += list(self._partition(i))[:n - len(out)]
        return out

    def top(self, n: int, key=None) -> List[Any]:
        return heapq.nlargest(n, self.collect(), key=key)

    def takeOrdered(self, n: int, key=None) -> List[Any]:
        return heapq.nsmallest(n, self.collect(), key=key)

    def isEmpty(self) -> bool:
        return all(not list(self._partition(i)) for i in range(self._num))

    def reduce(self, f):
        parts = [_freduce(f, p) for p in
                 (list(self._partition(i)) for i in range(self._num)) if p]
        if not parts:
            raise ValueError("cannot reduce empty RDD")
        return _freduce(f, parts)

    def fold(self, zeroValue, op):
        parts = [_freduce(op, list(self._partition(i)), zeroValue)
                 for i in range(self._num)]
        return _freduce(op, parts, zeroValue)

    def aggregate(self, zeroValue, seqOp, combOp):
        import copy
        parts = [_freduce(seqOp, list(self._partition(i)),
                          copy.deepcopy(zeroValue))
                 for i in range(self._num)]
        return _freduce(combOp, parts, copy.deepcopy(zeroValue))

    def treeAggregate(self, zeroValue, seqOp, combOp, depth: int = 2):
        """`RDD.treeAggregate:1125` — multi-level partial aggregation (the
        reference's allreduce analog; on device this is psum/reduce-scatter
        over the mesh — see spark_tpu.parallel.collective.psum_arrays)."""
        import copy
        if self._num == 0:
            return zeroValue
        partials = [_freduce(seqOp, list(self._partition(i)),
                             copy.deepcopy(zeroValue))
                    for i in range(self._num)]
        scale = max(int(math.ceil(len(partials) ** (1.0 / depth))), 2)
        while len(partials) > 1:
            groups = [partials[i:i + scale]
                      for i in range(0, len(partials), scale)]
            partials = [_freduce(combOp, g) for g in groups]
        return partials[0]

    def treeReduce(self, f, depth: int = 2):
        vals = self.collect()
        if not vals:
            raise ValueError("cannot reduce empty RDD")
        return _freduce(f, vals)

    def sum(self):  # noqa: A003
        return sum(self.collect())

    def mean(self):
        return self.stats().mean()

    def min(self, key=None):  # noqa: A003
        return min(self.collect(), key=key) if key else min(self.collect())

    def max(self, key=None):  # noqa: A003
        return max(self.collect(), key=key) if key else max(self.collect())

    def stdev(self):
        return self.stats().stdev()

    def variance(self):
        return self.stats().variance()

    def stats(self) -> StatCounter:
        return self.aggregate(StatCounter(),
                              lambda s, v: s.merge(v),
                              lambda a, b: a.mergeStats(b))

    def histogram(self, buckets):
        vals = [v for v in self.collect()]
        if isinstance(buckets, int):
            lo, hi = min(vals), max(vals)
            step = (hi - lo) / buckets
            edges = [lo + i * step for i in range(buckets)] + [hi]
        else:
            edges = list(buckets)
        counts = [0] * (len(edges) - 1)
        for v in vals:
            idx = bisect.bisect_right(edges, v) - 1
            if idx == len(counts):
                idx -= 1
            if 0 <= idx < len(counts):
                counts[idx] += 1
        return edges, counts

    def foreach(self, f) -> None:
        for x in self.collect():
            f(x)

    def foreachPartition(self, f) -> None:
        for i in range(self._num):
            f(iter(self._partition(i)))

    def lookup(self, key) -> List[Any]:
        return [v for k, v in self.collect() if k == key]

    def saveAsTextFile(self, path: str) -> None:
        os.makedirs(path, exist_ok=False)
        for i in range(self._num):
            with open(os.path.join(path, f"part-{i:05d}"), "w",
                      encoding="utf-8") as f:
                for x in self._partition(i):
                    f.write(str(x) + "\n")
        open(os.path.join(path, "_SUCCESS"), "w").close()

    # -- bridge to the accelerated path -----------------------------------
    def toDF(self, names: Optional[List[str]] = None):
        """Hop onto the columnar/TPU path (`SparkSession.createDataFrame`)."""
        session = self._sc._session()
        return session.createDataFrame(self.collect(), names)

    def __repr__(self):
        return f"{self._name}[{self.id}] at partitions={self._num}"


def _poisson(rng: random.Random, lam: float) -> int:
    # Knuth's algorithm (small lambda)
    L = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= L:
            return k
        k += 1
