"""RDD core API (`core/rdd/` analog): SparkContext, RDD, shared variables."""

from .context import Accumulator, AccumulatorParam, Broadcast, SparkContext
from .rdd import HashPartitioner, Partitioner, RDD, StatCounter

__all__ = [
    "SparkContext", "RDD", "Broadcast", "Accumulator", "AccumulatorParam",
    "Partitioner", "HashPartitioner", "StatCounter",
]
