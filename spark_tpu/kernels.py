"""Static-shape operator kernels over ColumnBatch.

These replace the reference's Tungsten execution layer — ``BytesToBytesMap``
hash aggregation (``unsafe/map/BytesToBytesMap.java:66``), radix sort
(``collection/unsafe/sort/RadixSort.java``), and the iterator-chain operators
— with XLA-friendly primitives:

* group-by is SORT-BASED: multi-key ``lax.sort`` → segment boundaries →
  ``segment_sum/min/max``.  Scatter-heavy hash maps fit TPUs poorly; sorting
  rides the hardware sort and keeps shapes static (Spark itself falls back to
  sort-based aggregation when its hash map fills —
  ``TungstenAggregationIterator.scala``).
* filter never compacts — it ANDs the row mask; ``compact`` is explicit.
* every kernel is pure and shape-static, so whole pipelines trace into one
  XLA program (the WholeStageCodegen analog).

All kernels take ``xp`` (numpy | jax.numpy) — the dual-path contract.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from . import types as T
from .aggregates import AggregateFunction, First, IDENTITY
from .columnar import (ColumnBatch, ColumnVector, PlaneColumnVector,
                       RunColumnVector, bump_run_aware, merge_dictionaries,
                       unexpanded_plane, unmaterialized_runs)
from .expressions import (Col, EvalContext, Expression, ExprValue, Rand,
                          RowIndex, SparkPartitionId)

Array = Any


def _is_np(xp) -> bool:
    return xp is np


# ---------------------------------------------------------------------------
# sorting primitives
# ---------------------------------------------------------------------------

def multi_key_argsort(xp, keys: Sequence[Array], capacity: int) -> Array:
    """Stable lexicographic argsort by keys[0], then keys[1], ...

    jax path: ``lax.sort`` (bitonic on TPU) over operands + iota;
    numpy path: ``np.lexsort`` (reversed key order).
    """
    if _is_np(xp):
        return np.lexsort(tuple(reversed([np.asarray(k) for k in keys])))
    import jax
    iota = xp.arange(capacity, dtype=np.int32)
    out = jax.lax.sort(tuple(keys) + (iota,), num_keys=len(keys),
                       is_stable=True)
    return out[-1]


def searchsorted(xp, a: Array, v: Array, side: str = "left") -> Array:
    """Straight-line searchsorted for jit-traced code.

    On a TPU device the jax lane forces ``method="scan_unrolled"``: an
    unrolled log2(n) compare/select binary search instead of jnp's default
    while-loop scan.  ``stablehlo.while`` around emulated-i64 carries is
    the one structural feature the q3 join program has that every
    TPU-compiling program (agg, sort) lacks — the prime suspect for the
    round-1..4 remote-compile HTTP 500 — and straight-line code is also
    what XLA:TPU schedules best.  On CPU the while-loop scan stays: it
    measured 2.3x faster there (bench q3 lane, r5).  numpy lane: plain
    ``np.searchsorted``."""
    if _is_np(xp):
        return np.searchsorted(np.asarray(a), np.asarray(v), side=side)
    import os
    method = os.environ.get("SPARK_TPU_SEARCHSORTED") \
        or ("scan_unrolled" if _on_tpu_device() else "scan")
    return xp.searchsorted(a, v, side=side, method=method)


def radix_argsort(xp, keys: Array, bits: int = 4) -> Array:
    """Stable LSD radix argsort of int64 keys — the TPU-native candidate
    replacement for the bitonic ``lax.sort`` (`SortBenchmark.scala:120`
    radix baseline role).

    Per digit pass: a (n, 2^bits) one-hot, column sums for the global
    digit starts, an exclusive cumsum down the rows for stable
    within-digit ranks, and one scatter to invert the placement — all
    dense, fusable ops (the one-hot contraction is MXU-shaped), no
    compare network.  ``bits=4`` keeps the per-pass working set at
    n x 16 x 4B; 16 passes cover 64 bits.  CPU lane: np.argsort
    (XLA:CPU executes the dense formulation slower than its built-in
    sort — this path exists for TPU, A/B'd by tools/prof_agg2.py in a
    hardware window before it takes over any default)."""
    if _is_np(xp):
        return np.argsort(np.asarray(keys), kind="stable")
    if 64 % bits != 0:
        raise ValueError(f"radix_argsort bits={bits} must divide 64 "
                         "(uncovered top bits would silently mis-sort)")
    import jax
    import jax.numpy as jnp
    n = keys.shape[0]
    R = 1 << bits
    k = keys.astype(jnp.uint64) ^ jnp.uint64(1 << 63)   # signed → biased
    perm = jnp.arange(n, dtype=jnp.int32)
    for p in range(64 // bits):
        digit = ((k >> jnp.uint64(p * bits))
                 & jnp.uint64(R - 1)).astype(jnp.int32)
        oh = jax.nn.one_hot(digit, R, dtype=jnp.int32)          # (n, R)
        counts = oh.sum(axis=0)
        starts = jnp.cumsum(counts) - counts                    # (R,)
        ranks = jnp.cumsum(oh, axis=0) - oh                     # exclusive
        pos = starts[digit] + jnp.take_along_axis(
            ranks, digit[:, None], axis=1)[:, 0]
        inv = jnp.zeros(n, jnp.int32).at[pos].set(
            jnp.arange(n, dtype=jnp.int32))
        k = k[inv]
        perm = perm[inv]
    return perm


def sort_key_transform(xp, data: Array, valid: Optional[Array], dtype: T.DataType,
                       ascending: bool, nulls_first: bool) -> List[Array]:
    """Turn one sort column into (null_rank, comparable_key) arrays.

    Dead rows (row_valid=False) are pushed to the very end by the caller's
    leading dead-key.  Descending order flips integer bits (``~x``) /
    negates floats, mirroring the prefix trick of ``PrefixComparators.java``.
    """
    np_dt = np.asarray(data).dtype if _is_np(xp) else data.dtype
    if np_dt == np.bool_:
        data = data.astype(np.int8)
        np_dt = np.dtype(np.int8)
    if ascending:
        key = data
    else:
        if np.issubdtype(np_dt, np.floating):
            key = -data
        else:
            key = ~data
    if valid is None:
        null_rank = xp.zeros(data.shape[0], np.int8)
    else:
        # null_rank orders: nulls_first → nulls get -1 else +1
        rank_null = np.int8(-1) if nulls_first else np.int8(1)
        null_rank = xp.where(valid, np.int8(0), rank_null)
        ident = IDENTITY["min"](np_dt) if nulls_first else IDENTITY["max"](np_dt)
        key = xp.where(valid, key, np.asarray(ident, np_dt))
    return [null_rank, key]


def sort_batch(xp, batch: ColumnBatch,
               keys: Sequence[Tuple[Array, Optional[Array], T.DataType, bool, bool]],
               ) -> ColumnBatch:
    """Sort live rows by the given key specs; dead rows sink to the end.

    keys: (data, valid, dtype, ascending, nulls_first) per sort column.
    """
    dead = ~batch.row_valid_or_true()
    sort_cols: List[Array] = [dead.astype(np.int8)]
    for data, valid, dtype, asc, nf in keys:
        sort_cols += sort_key_transform(xp, data, valid, dtype, asc, nf)
    perm = multi_key_argsort(xp, sort_cols, batch.capacity)
    return take_batch(xp, batch, perm)


def range_bucket(xp, keys: Array, cuts: Array) -> Array:
    """Map orderable int64 join keys to contiguous span ids by binary
    search against shared cut points (RangePartitioner.getPartition
    analog, jittable).

    ``cuts`` are the ``n_spans - 1`` strictly-increasing EXCLUSIVE upper
    bounds every process derived identically from the sample round: span
    id = number of cut points ≤ the key (``side="right"``), so every
    duplicate of a value — hot keys included — lands in ONE span on
    every process.  Composes with ``partition_bucket``: the returned
    int32 span ids are that kernel's ``part_ids``.
    """
    return searchsorted(xp, cuts, keys, side="right").astype(np.int32)


def partition_bucket(xp, batch: ColumnBatch, part_ids: Array,
                     n_parts: int,
                     tie_keys: Optional[Sequence[Array]] = None,
                     ) -> Tuple[ColumnBatch, Array, Array]:
    """Bucket rows by partition id in ONE device sort (the exchange-side
    replacement for per-receiver host mask/compact passes).

    Dead rows fold into a virtual partition ``n_parts`` so a single-key
    stable sort (riding ``multi_key_argsort``'s lax.sort path) groups
    live rows contiguously by destination with padding at the tail.
    Returns ``(bucketed, offsets, counts)``: partition ``p``'s rows are
    ``bucketed[offsets[p] : offsets[p] + counts[p]]``, so the sender
    does one compacted D2H transfer and slices per-receiver host VIEWS
    out of it — padding never crosses DCN.  ``tie_keys`` appends extra
    sort keys AFTER the partition id, ordering rows WITHIN each bucket
    (the range exchange ships key-sorted runs this way — same single
    sort, no extra pass).  Jittable on the jnp path (``n_parts``
    static); numpy path is the host fallback.
    """
    live = batch.row_valid_or_true()
    pid = xp.where(live, xp.asarray(part_ids).astype(np.int32),
                   np.int32(n_parts))
    sort_keys = [pid] + [xp.asarray(k) for k in (tie_keys or [])]
    perm = multi_key_argsort(xp, sort_keys, batch.capacity)
    bucketed = take_batch(xp, batch, perm)
    if _is_np(xp):
        counts = np.bincount(np.asarray(pid)[np.asarray(live)],
                             minlength=n_parts).astype(np.int32)
    else:
        # dead rows carry pid == n_parts; out-of-bounds scatter adds drop
        counts = xp.zeros(n_parts, np.int32).at[pid].add(
            np.int32(1), mode="drop")
    offsets = xp.concatenate(
        [xp.zeros(1, np.int32), xp.cumsum(counts)[:-1].astype(np.int32)])
    return bucketed, offsets, counts


def partition_host_slices(xp, batch: ColumnBatch, part_ids: Array,
                          n_parts: int,
                          tie_keys: Optional[Sequence[Array]] = None,
                          ) -> Tuple[ColumnBatch, Array, Array]:
    """``partition_bucket`` + one D2H transfer + host offset/count arrays.

    The shared front half of every DCN route (aggregate-state exchange,
    shuffled-join co-partitioning): callers carve zero-copy per-receiver
    views out of the returned host batch with ``slice_rows``.  Because
    the bucketing sort is stable and partition ids ascend, any CONTIGUOUS
    range of partitions is itself one contiguous slice — which is what
    lets the manifest coordinator coalesce adjacent fine partitions into
    a single receiver block without re-bucketing.
    """
    bucketed, offsets, counts = partition_bucket(xp, batch, part_ids,
                                                 n_parts, tie_keys)
    return (bucketed.to_host(), np.asarray(offsets), np.asarray(counts))


def slice_rows(batch: ColumnBatch, start: int, count: int) -> ColumnBatch:
    """A zero-copy HOST view of rows ``[start, start + count)`` — numpy
    basic slicing, every column shares the parent's buffers.  Rows in the
    window are assumed live (``partition_bucket`` guarantees it), so the
    view drops the row mask."""
    vectors = [
        ColumnVector(np.asarray(v.data)[start:start + count], v.dtype,
                     None if v.valid is None
                     else np.asarray(v.valid)[start:start + count],
                     v.dictionary)
        for v in batch.vectors
    ]
    return ColumnBatch(list(batch.names), vectors, None, count)


def take_batch(xp, batch: ColumnBatch, perm: Array) -> ColumnBatch:
    """Gather all columns (and masks) through an index array.

    ``perm`` may be longer/shorter than the input capacity (join expansion);
    the output capacity is ``len(perm)``.
    """
    out_cap = int(perm.shape[0])
    vectors = []
    for v in batch.vectors:
        data = v.data[perm]
        valid = None if v.valid is None else v.valid[perm]
        vectors.append(ColumnVector(data, v.dtype, valid, v.dictionary))
    rv = None if batch.row_valid is None else batch.row_valid[perm]
    return ColumnBatch(batch.names, vectors, rv, out_cap)


def compact(xp, batch: ColumnBatch) -> ColumnBatch:
    """Move live rows to the front, preserving order (stable).

    Device path: ONE single-operand uint32 sort — the dead flag rides
    the iota's top bit (capacity < 2^31 always), so the sorted values
    ARE the permutation: live rows (bit clear) sort first in original
    order, dead rows after.  Half the comparator/permute work of the
    two-operand (flag, iota) formulation on the TPU's bitonic sort."""
    if batch.row_valid is None:
        return batch
    if _is_np(xp):
        dead = (~batch.row_valid).astype(np.int8)
        perm = multi_key_argsort(xp, [dead], batch.capacity)
        return take_batch(xp, batch, perm)
    import jax
    dead = ~batch.row_valid
    iota = xp.arange(batch.capacity, dtype=np.uint32)
    packed = iota | (dead.astype(np.uint32) << np.uint32(31))
    (packed_s,) = jax.lax.sort((packed,), num_keys=1, is_stable=False)
    perm = (packed_s & np.uint32(0x7FFFFFFF)).astype(np.int32)
    return take_batch(xp, batch, perm)


# ---------------------------------------------------------------------------
# run-length / delta codecs (the wire.py "enc" tags; see RunColumnVector)
# ---------------------------------------------------------------------------

def rle_encode(data: Array) -> Tuple[np.ndarray, np.ndarray]:
    """Run-length encode a 1-D host array into ``(run_values, run_lengths)``.

    Run detection is ONE vectorized diff + nonzero — no Python loop."""
    data = np.asarray(data)
    n = len(data)
    if n == 0:
        return data[:0], np.zeros(0, np.int64)
    change = np.nonzero(data[1:] != data[:-1])[0] + 1
    starts = np.concatenate([np.zeros(1, np.int64), change])
    lengths = np.diff(np.concatenate([starts, np.asarray([n], np.int64)]))
    return data[starts], lengths.astype(np.int64)


def rle_expand(xp, run_values: Array, run_lengths: Array) -> Array:
    """Expand a run table back to the dense array (cumsum/repeat only, so
    the jax lane traces when the output length is static)."""
    return xp.repeat(xp.asarray(run_values), xp.asarray(run_lengths))


def run_row_ids(xp, plane_lengths: Array, capacity: int) -> Array:
    """Row → run-index map for a fixed-capacity run plane (shape-stable,
    jittable): inclusive-cumsum the zero-padded lengths into run END
    offsets, then binary-search each row position right of its end.
    Zero-length (padded) runs collapse to repeated ends that the
    ``side="right"`` search skips, so every row lands on a REAL run.
    O(capacity · log planes) compares, no scatter."""
    ends = xp.cumsum(xp.asarray(plane_lengths).astype(np.int64))
    rows = xp.arange(capacity, dtype=np.int64)
    ids = searchsorted(xp, ends, rows, side="right")
    # rows past sum(lengths) (never produced by a well-formed plane) clamp
    # into range instead of indexing out of bounds
    return xp.clip(ids, 0, max(int(plane_lengths.shape[0]) - 1, 0))


def run_expand(xp, plane_values: Array, plane_lengths: Array,
               capacity: int) -> Array:
    """Searchsorted-gather expansion of a run plane to its dense array —
    the jit-lane analog of ``rle_expand`` (whose ``repeat`` needs a data-
    dependent output length).  numpy lane: plain repeat (exact and
    cheaper on host)."""
    if _is_np(xp):
        return np.repeat(np.asarray(plane_values),
                         np.asarray(plane_lengths))[:capacity]
    return xp.asarray(plane_values)[run_row_ids(xp, plane_lengths, capacity)]


def delta_encode(data: Array) -> Optional[Tuple[int, np.ndarray]]:
    """Delta / frame-of-reference encode a 1-D signed-int host array as
    ``(base, diffs)`` with diffs downcast to the narrowest of
    int8/int16/int32 that bounds them.  Diffs are taken in int64 modular
    arithmetic, so ``delta_decode``'s cumsum reconstruction is exact even
    across wraparound.  Returns None when no strictly narrower diff dtype
    exists (encoding would not shrink the column)."""
    data = np.asarray(data)
    if len(data) < 2:
        return None
    d64 = np.diff(data.astype(np.int64))
    lo, hi = int(d64.min()), int(d64.max())
    for cand in (np.int8, np.int16, np.int32):
        if np.dtype(cand).itemsize >= data.dtype.itemsize:
            break
        info = np.iinfo(cand)
        if info.min <= lo and hi <= info.max:
            return int(data[0]), d64.astype(cand)
    return None


def delta_decode(xp, base: int, diffs: Array, np_dtype, n: int) -> Array:
    """cumsum reconstruction of a delta-encoded column; exact under int64
    modular arithmetic regardless of the original dtype's wraparound."""
    if n == 0:
        return xp.zeros(0, np_dtype)
    d = xp.asarray(diffs).astype(np.int64)
    prefix = xp.concatenate([xp.zeros(1, np.int64), xp.cumsum(d)])
    return (np.int64(base) + prefix).astype(np_dtype)


# ---------------------------------------------------------------------------
# row-mask operators
# ---------------------------------------------------------------------------

#: expression classes whose value depends on the row's POSITION rather than
#: the row's data — a run head cannot stand in for its whole run under these
#: (Randn subclasses Rand; all four read ``ctx.row_offset``)
_POSITIONAL_EXPRS = (Rand, RowIndex, SparkPartitionId)


def _run_aware_filter(batch: ColumnBatch,
                      pred: Expression) -> Optional[ColumnBatch]:
    """Evaluate ``pred`` once per run head and expand the selection mask by
    run length.  Applies when the predicate references exactly one column,
    that column is an unexpanded run vector covering the batch, and the
    predicate is data-deterministic (no positional expressions).  Returns
    None to fall back to the dense path."""
    refs = pred.references()
    if len(refs) != 1:
        return None
    name = next(iter(refs))
    if name not in batch.names:
        return None
    rv = unmaterialized_runs(batch.column(name))
    if rv is None or rv.valid is not None or rv.capacity != batch.capacity:
        return None
    stack = [pred]
    while stack:
        e = stack.pop()
        if isinstance(e, _POSITIONAL_EXPRS):
            return None
        stack.extend(e.children)
    n_runs = len(rv.run_values)
    head = ColumnBatch(
        [name], [ColumnVector(rv.run_values, rv.dtype, None, rv.dictionary)],
        None, n_runs)
    v = pred.eval(EvalContext(head, np))
    keep = np.broadcast_to(np.asarray(v.data), (n_runs,))
    if v.valid is not None:
        keep = keep & np.broadcast_to(np.asarray(v.valid), (n_runs,))
    keep = np.repeat(keep.astype(bool), rv.run_lengths)
    bump_run_aware(batch.capacity)
    out_rv = np.asarray(batch.row_valid_or_true()) & keep
    return ColumnBatch(batch.names, batch.vectors, out_rv, batch.capacity)


def _plane_filter(xp, batch: ColumnBatch,
                  pred: Expression) -> Optional[ColumnBatch]:
    """Jit-lane twin of ``_run_aware_filter``: evaluate ``pred`` once per
    run HEAD of a device plane, then expand only the boolean keep mask
    through ``run_row_ids`` — the data column never expands.  Applies
    when the predicate references exactly one column, that column is an
    unexpanded run plane covering the batch, and the predicate is
    data-deterministic (no positional expressions).  Returns None to
    fall back to the dense path (which expands in-trace, counted)."""
    refs = pred.references()
    if len(refs) != 1:
        return None
    name = next(iter(refs))
    if name not in batch.names:
        return None
    pv = unexpanded_plane(batch.column(name))
    if pv is None or pv.valid is not None or pv.capacity != batch.capacity:
        return None
    stack = [pred]
    while stack:
        e = stack.pop()
        if isinstance(e, _POSITIONAL_EXPRS):
            return None
        stack.extend(e.children)
    plane_cap = pv.plane_capacity
    head = ColumnBatch(
        [name],
        [ColumnVector(pv.plane_values, pv.dtype, None, pv.dictionary)],
        None, plane_cap)
    v = pred.eval(EvalContext(head, xp))
    keep = xp.broadcast_to(v.data, (plane_cap,))
    if v.valid is not None:
        keep = keep & xp.broadcast_to(v.valid, (plane_cap,))
    keep_rows = keep.astype(bool)[run_row_ids(xp, pv.plane_lengths,
                                              batch.capacity)]
    out_rv = batch.row_valid_or_true() & keep_rows
    return ColumnBatch(batch.names, batch.vectors, out_rv, batch.capacity)


def apply_filter(xp, batch: ColumnBatch, pred: Expression,
                 row_offset: int = 0) -> ColumnBatch:
    if _is_np(xp) and row_offset == 0:
        out = _run_aware_filter(batch, pred)
        if out is not None:
            return out
    if not _is_np(xp):
        # no row_offset gate: the offset only feeds positional
        # expressions, which _plane_filter already refuses
        out = _plane_filter(xp, batch, pred)
        if out is not None:
            return out
    ctx = EvalContext(batch, xp, row_offset)
    v = pred.eval(ctx)
    keep = v.data
    if v.valid is not None:
        keep = keep & v.valid          # NULL predicate → drop (SQL WHERE)
    rv = batch.row_valid_or_true() & keep
    return ColumnBatch(batch.names, batch.vectors, rv, batch.capacity)


def apply_project(xp, batch: ColumnBatch, exprs: Sequence[Expression],
                  row_offset: int = 0) -> ColumnBatch:
    ctx = EvalContext(batch, xp, row_offset)
    names, vectors = [], []
    schema = batch.schema
    for e in exprs:
        if isinstance(e, Col) and e._name in batch.names:
            # bare column select keeps run forms (plane or host run table)
            # un-inflated — evaluating through EvalContext would expand
            src = batch.column(e._name)
            if (unexpanded_plane(src) is not None
                    or unmaterialized_runs(src) is not None) \
                    and src.dtype.np_dtype == e.data_type(schema).np_dtype:
                names.append(e.name)
                vectors.append(src)
                continue
        v = ctx.broadcast(e.eval(ctx))
        dt = e.data_type(schema)
        names.append(e.name)
        vectors.append(ColumnVector(v.data.astype(dt.np_dtype), dt, v.valid,
                                    v.dictionary))
    return ColumnBatch(names, vectors, batch.row_valid, batch.capacity)


def apply_limit(xp, batch: ColumnBatch, n: int) -> ColumnBatch:
    rv = batch.row_valid_or_true()
    keep = xp.cumsum(rv.astype(np.int64)) <= n
    return ColumnBatch(batch.names, batch.vectors, rv & keep, batch.capacity)


# ---------------------------------------------------------------------------
# segment reductions
# ---------------------------------------------------------------------------

def _np_segment_reduce(data: np.ndarray, seg: np.ndarray, num: int, kind: str,
                       ident) -> np.ndarray:
    out = np.full(num, ident, dtype=data.dtype)
    if kind == "sum":
        np.add.at(out, seg, data)
    elif kind == "min":
        np.minimum.at(out, seg, data)
    else:
        np.maximum.at(out, seg, data)
    return out


def _global_reduce(xp, data: Array, kind: str, capacity: int) -> Array:
    """One-segment reduction: the whole (already contribute-masked)
    buffer collapses to slot 0; remaining slots hold the identity, as
    segment_reduce would leave them.  No sort, no scatter."""
    np_dt = np.asarray(data).dtype if _is_np(xp) else np.dtype(str(data.dtype))
    ident = IDENTITY[kind](np_dt)
    if capacity == 0:
        # capacity-0 host batches: segment_reduce returned shape (0,)
        return xp.zeros(0, np_dt)
    if kind == "sum":
        val = data.sum()
    elif kind == "min":
        val = data.min()
    else:
        val = data.max()
    rest = xp.full(capacity - 1, ident, np_dt)
    return xp.concatenate([xp.asarray(val).reshape(1).astype(np_dt), rest])


def segment_reduce(xp, data: Array, seg_ids: Array, num_segments: int,
                   kind: str) -> Array:
    np_dt = np.asarray(data).dtype if _is_np(xp) else np.dtype(str(data.dtype))
    ident = IDENTITY[kind](np_dt)
    if _is_np(xp):
        return _np_segment_reduce(np.asarray(data), np.asarray(seg_ids),
                                  num_segments, kind, ident)
    import jax
    if kind == "sum":
        return jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)
    if kind == "min":
        return jax.ops.segment_min(data, seg_ids, num_segments=num_segments)
    return jax.ops.segment_max(data, seg_ids, num_segments=num_segments)


# ---------------------------------------------------------------------------
# grouped aggregation (sort-based HashAggregateExec replacement)
# ---------------------------------------------------------------------------

#: the one-hot-matmul aggregation only wins where a systolic array exists.
#: None = auto (TPU backends only); tests force True to exercise the MXU
#: kernel on the virtual CPU mesh.
MXU_AGG_ENABLED: "bool | None" = None


def _mxu_agg_on() -> bool:
    if MXU_AGG_ENABLED is not None:
        return MXU_AGG_ENABLED
    try:
        return jax.default_backend() not in ("cpu",)
    except Exception:
        return False


def _on_tpu_device() -> bool:
    """True when the default device is a real TPU.  Checked via the
    DEVICE platform, not ``jax.default_backend()``: tunnel plugins (the
    axon backend) register under their own backend name while exposing
    ``platform == "tpu"`` devices, and Mosaic kernels key off the
    hardware, not the transport."""
    try:
        if jax.default_backend() == "tpu":
            return True
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def grouped_aggregate(
    xp,
    batch: ColumnBatch,
    key_exprs: Sequence[Expression],
    agg_slots: Sequence[Tuple[AggregateFunction, str]],
    bucket_cap: int = 4096,
) -> ColumnBatch:
    """GROUP BY keys with aggregate outputs; one batch in, one batch out.

    With keys, output capacity equals input capacity (worst case: every live
    row its own group) and ``row_valid`` marks real groups.  NULL is a group
    key value (SQL semantics).  With no keys, the single global-aggregate
    row comes back as a capacity-1 batch (see `_sorted_grouped_aggregate`).

    Device path: when keys are integral and the key range fits ``bucket_cap``
    buckets, aggregation runs on the MXU (one-hot matmul over 8-bit limb
    planes — see ``_mxu_grouped_aggregate``); a runtime ``lax.cond`` falls
    back to the sort-based path otherwise.
    """
    if _mxu_agg_on() and not _is_np(xp) and key_exprs \
            and _mxu_applicable(batch.schema, key_exprs, agg_slots):
        return _mxu_grouped_aggregate(xp, batch, key_exprs, agg_slots,
                                      bucket_cap)
    if _is_np(xp) and not key_exprs:
        out = _run_aware_global_aggregate(batch, agg_slots)
        if out is not None:
            return out
    if not _is_np(xp) and not key_exprs:
        out = _plane_global_aggregate(xp, batch, agg_slots)
        if out is not None:
            return out
    return _sorted_grouped_aggregate(xp, batch, key_exprs, agg_slots)


def _run_aware_global_aggregate(
    batch: ColumnBatch,
    agg_slots: Sequence[Tuple[AggregateFunction, str]],
) -> Optional[ColumnBatch]:
    """Keyless count/sum over run-encoded columns without expansion: a run
    contributes ``value × length`` with one multiply.  Fires only when the
    result is provably byte-identical to the dense path — every slot is
    count(*)/count/sum (non-distinct) over a bare column whose vector is an
    unexpanded run table with no NULLs covering a fully-live batch; integer
    sums match the dense path exactly because int64 products and sums both
    wrap mod 2^64 (floats are excluded: their addition is not associative).
    Returns None to fall back to the general path."""
    from .aggregates import Count, CountStar, Sum
    if batch.row_valid is not None or batch.capacity == 0 or not agg_slots:
        return None
    cap = batch.capacity
    plans = []
    for func, name in agg_slots:
        if getattr(func, "is_distinct", False):
            return None
        if isinstance(func, CountStar):
            plans.append((func, name, None))
            continue
        if type(func) not in (Count, Sum):
            return None
        child = func.children[0]
        if not isinstance(child, Col) or child.name not in batch.names:
            return None
        rv = unmaterialized_runs(batch.column(child.name))
        if rv is None or rv.valid is not None or rv.capacity != cap:
            return None
        if isinstance(func, Sum) \
                and np.asarray(rv.run_values).dtype.kind not in "iub":
            return None
        plans.append((func, name, rv))
    if all(rv is None for _, _, rv in plans):
        return None  # nothing run-encoded: nothing to claim credit for
    schema = batch.schema
    names: List[str] = []
    vectors: List[ColumnVector] = []
    for func, name, rv in plans:
        dt = func.data_type(schema)
        if rv is None or isinstance(func, (CountStar, Count)):
            # no NULLs and no dead rows ⇒ count == capacity
            out = ColumnVector(np.asarray([cap], dt.np_dtype), dt, None, None)
        else:
            out_np = dt.np_dtype
            total = (np.asarray(rv.run_values).astype(out_np)
                     * rv.run_lengths.astype(out_np)).sum(dtype=out_np)
            out = ColumnVector(np.asarray([total], out_np), dt,
                               np.asarray([True]), None)
        names.append(name)
        vectors.append(out)
    bump_run_aware(cap)
    return ColumnBatch(names, vectors, None, 1)


def _plane_global_aggregate(
    xp,
    batch: ColumnBatch,
    agg_slots: Sequence[Tuple[AggregateFunction, str]],
) -> Optional[ColumnBatch]:
    """Jit-lane twin of ``_run_aware_global_aggregate`` over run PLANES,
    extended with min/max and a dense row mask: keyless count/sum reduce
    ``run_values × per-run-live-counts`` (the live counts come from one
    ``segment_sum`` of the row mask over ``run_row_ids``), min/max reduce
    the run VALUES under a per-run any-live mask — no arithmetic on
    expanded rows, so exact for every dtype.  Fires only when provably
    byte-identical to the dense path: every slot is count(*)/count/sum/
    min/max (non-distinct) over a bare column whose vector is an
    unexpanded plane with no NULLs covering the batch; integer-only sums
    (int64 products and sums both wrap mod 2^64; float addition is not
    associative).  Returns None to fall back (in-trace expansion,
    counted)."""
    from .aggregates import Count, CountStar, Max, Min, Sum
    if batch.capacity == 0 or not agg_slots:
        return None
    cap = batch.capacity
    plans = []
    for func, name in agg_slots:
        if getattr(func, "is_distinct", False):
            return None
        if isinstance(func, CountStar):
            plans.append((func, name, None))
            continue
        if type(func) not in (Count, Sum, Min, Max):
            return None
        child = func.children[0]
        if not isinstance(child, Col) or child._name not in batch.names:
            return None
        pv = unexpanded_plane(batch.column(child._name))
        if pv is None or pv.valid is not None or pv.capacity != cap:
            return None
        if isinstance(func, Sum) \
                and np.dtype(pv.dtype.np_dtype).kind not in "iub":
            return None
        plans.append((func, name, pv))
    if all(pv is None for _, _, pv in plans):
        return None  # nothing plane-encoded: nothing to claim credit for

    live = batch.row_valid  # row masks are always dense, never planes
    n_live = np.int64(cap) if live is None else xp.sum(live.astype(np.int64))
    counts_cache: dict = {}

    def run_live_counts(pv: PlaneColumnVector) -> Array:
        """Live-row count per run slot (zero on padded slots)."""
        if id(pv) not in counts_cache:
            if live is None:
                c = xp.asarray(pv.plane_lengths).astype(np.int64)
            else:
                ids = run_row_ids(xp, pv.plane_lengths, cap)
                c = segment_reduce(xp, live.astype(np.int64), ids,
                                   pv.plane_capacity, "sum")
            counts_cache[id(pv)] = c
        return counts_cache[id(pv)]

    def as1(val, np_dt):
        return xp.asarray(val).reshape(1).astype(np_dt)

    schema = batch.schema
    names: List[str] = []
    vectors: List[ColumnVector] = []
    for func, name, pv in plans:
        dt = func.data_type(schema)
        if pv is None or isinstance(func, (CountStar, Count)):
            # no NULLs ⇒ count == number of live rows
            out = ColumnVector(as1(n_live, dt.np_dtype), dt, None, None)
        elif isinstance(func, Sum):
            out_np = dt.np_dtype
            total = (xp.asarray(pv.plane_values).astype(out_np)
                     * run_live_counts(pv).astype(out_np)).sum()
            out = ColumnVector(as1(total, out_np), dt,
                               as1(n_live > 0, np.bool_), None)
        else:  # Min / Max
            red_dt = np.dtype(np.int8) if dt.np_dtype == np.bool_ \
                else np.dtype(dt.np_dtype)
            ident = IDENTITY[func.kind](red_dt)
            run_live = run_live_counts(pv) > 0
            buf = xp.where(run_live,
                           xp.asarray(pv.plane_values).astype(red_dt),
                           xp.asarray(ident, red_dt))
            val = buf.min() if func.kind == "min" else buf.max()
            out = ColumnVector(as1(val, dt.np_dtype), dt,
                               as1(n_live > 0, np.bool_), pv.dictionary)
        names.append(name)
        vectors.append(out)
    return ColumnBatch(names, vectors, None, 1)


def _sorted_grouped_aggregate(
    xp,
    batch: ColumnBatch,
    key_exprs: Sequence[Expression],
    agg_slots: Sequence[Tuple[AggregateFunction, str]],
) -> ColumnBatch:
    """Sort-based grouping: multi-key sort → segment boundaries → segment
    reduce (the general path; also the numpy oracle)."""
    if not key_exprs and batch.capacity == 0:
        # the global row exists even over an empty input (COUNT=0, SUM
        # NULL); pad to one all-dead row so the ordinary no-live-rows
        # machinery produces it (a capacity-0 batch cannot hold it)
        from .columnar import pad_to_capacity
        batch = pad_to_capacity(batch, 1)
    ctx = EvalContext(batch, xp)
    capacity = batch.capacity
    live = batch.row_valid_or_true()
    schema = batch.schema

    # ---- evaluate keys and build the composite sort key -----------------
    key_vals: List[ExprValue] = [ctx.broadcast(k.eval(ctx)) for k in key_exprs]
    sort_cols: List[Array] = [(~live).astype(np.int8)]
    for v in key_vals:
        data = v.data
        if (np.asarray(data).dtype if _is_np(xp) else data.dtype) == np.bool_:
            data = data.astype(np.int8)
        if v.valid is None:
            sort_cols += [xp.zeros(capacity, np.int8), data]
        else:
            # NULL forms its own group; rank it before all values
            sort_cols += [xp.where(v.valid, np.int8(0), np.int8(-1)),
                          xp.where(v.valid, data, xp.zeros((), data.dtype))]
    # keyless (global) aggregation needs NO sort: every buffer reduces
    # over one segment, and the reductions are order-independent (First
    # reduces original-row indices).  The sort was the dominant cost of
    # every global aggregate — a full O(n log^2 n) bitonic pass on TPU
    # for a single output row.
    perm = multi_key_argsort(xp, sort_cols, capacity) if key_exprs else None

    sorted_cols = sort_cols if perm is None else [c[perm] for c in sort_cols]
    live_s = live if perm is None else live[perm]

    # ---- segment boundaries --------------------------------------------
    if key_exprs:
        change = xp.zeros(capacity, bool)
        for c in sorted_cols:
            shifted = xp.concatenate([c[:1], c[:-1]])
            change = change | (c != shifted)
        is_start = change
        if _is_np(xp):
            is_start = is_start.copy()
            is_start[0] = True
        else:
            is_start = is_start.at[0].set(True)
        is_start = is_start & live_s
        seg_ids = xp.cumsum(is_start.astype(np.int64)) - 1
        seg_ids = xp.where(live_s, seg_ids, np.int64(capacity - 1))
        num_groups = xp.sum(is_start.astype(np.int64))
    else:
        seg_ids = xp.zeros(capacity, np.int64)
        is_start = None
        num_groups = None  # exactly one global group

    # ---- reduce buffers --------------------------------------------------
    out_names: List[str] = []
    out_vectors: List[ColumnVector] = []

    # key output columns: value at each segment start scattered to group slot
    group_pos = xp.arange(capacity, dtype=np.int64)
    for k, v in zip(key_exprs, key_vals):
        dt = k.data_type(schema)
        data_s = ctx.broadcast(v).data[perm]
        valid_s = None if v.valid is None else v.valid[perm]
        kdata = _scatter_starts(xp, data_s, seg_ids, is_start, capacity)
        kvalid = None if valid_s is None else _scatter_starts(
            xp, valid_s, seg_ids, is_start, capacity)
        out_names.append(k.name)
        out_vectors.append(ColumnVector(kdata.astype(dt.np_dtype), dt, kvalid,
                                        v.dictionary))

    contribute = live
    # all percentile slots over one child share ONE value-sort
    pct_slots = [(f, n) for f, n in agg_slots
                 if getattr(f, "is_percentile", False)]
    pct_results = {}
    if pct_slots:
        by_child = {}
        for f, n in pct_slots:
            by_child.setdefault(repr(f.children[0]), []).append((f, n))
        for group in by_child.values():
            pct_results.update(_percentile_groups(
                xp, ctx, group, sort_cols, live, capacity))
    for func, name in agg_slots:
        if getattr(func, "is_percentile", False):
            out_names.append(name)
            out_vectors.append(pct_results[name])
            continue
        if getattr(func, "is_collect", False):
            cperm = perm if perm is not None \
                else xp.arange(capacity, dtype=np.int64)
            out_names.append(name)
            out_vectors.append(_collect_into_arrays(
                xp, ctx, func, cperm, sort_cols, seg_ids, is_start,
                group_pos, live_s, capacity))
            continue
        specs = func.make_buffers(ctx, contribute)
        if perm is None:
            reduced = [_global_reduce(xp, s.data, s.kind, capacity)
                       for s in specs]
        else:
            sorted_bufs = [s.data[perm] for s in specs]
            reduced = [segment_reduce(xp, b, seg_ids, capacity, s.kind)
                       for b, s in zip(sorted_bufs, specs)]
        dt = func.data_type(schema)
        if isinstance(func, First):
            # argmin/argmax of row index → gather the value column
            v = ctx.broadcast(func.children[0].eval(ctx))
            idx = xp.clip(reduced[0], 0, capacity - 1).astype(np.int64)
            # reduced index is in PRE-sort coordinates (buffers built pre-sort
            # then permuted; values stored are original indices)
            data = v.data[idx]
            got = (reduced[0] >= 0) & (reduced[0] < np.int64(1 << 62))
            valid = got if v.valid is None else (got & v.valid[idx])
            out = ExprValue(data, valid, v.dictionary)
        else:
            out = func.finish(xp, reduced)
        dictionary = out.dictionary if out.dictionary is not None \
            else func.output_dictionary(ctx)
        data = out.data.astype(dt.np_dtype) if dt.np_dtype != np.bool_ \
            else out.data.astype(np.bool_)
        out_names.append(name)
        out_vectors.append(ColumnVector(data, dt, out.valid, dictionary))

    # ---- output row mask -------------------------------------------------
    if key_exprs:
        out_rv = group_pos < num_groups
        return ColumnBatch(out_names, out_vectors, out_rv, capacity)
    # keyless (global) aggregation: exactly ONE row, so emit capacity 1 —
    # cross joins of scalar subquery blocks (TPC-DS q88/q90) stay tiny
    # instead of multiplying input capacities
    out_vectors = [
        ColumnVector(v.data[:1], v.dtype,
                     None if v.valid is None else v.valid[:1], v.dictionary)
        for v in out_vectors
    ]
    return ColumnBatch(out_names, out_vectors, None, 1)


def _percentile_groups(xp, ctx, slots, sort_cols, live, capacity: int
                       ) -> dict:
    """Exact nearest-rank percentiles per group, ONE value-sort for every
    requested percentage over the same child: re-sort by (keys, value) so
    each group's values are ordered, then gather the row whose
    position-in-group equals floor(p * (n_valid - 1)).  Returns
    {slot_name: ColumnVector}."""
    func = slots[0][0]
    v = ctx.broadcast(func.children[0].eval(ctx))
    vdata = v.data
    np_dt = np.asarray(vdata).dtype if _is_np(xp) else \
        np.dtype(str(vdata.dtype))
    if np_dt == np.bool_:
        vdata = vdata.astype(np.int8)
        np_dt = np.dtype(np.int8)
    keep = live if v.valid is None else (live & v.valid)
    # NULL/dead values sort to the end of their group (max-identity key)
    ident = IDENTITY["max"](np_dt)
    vkey = xp.where(keep, vdata, np.asarray(ident, vdata.dtype))
    vnull = xp.where(keep, np.int8(0), np.int8(1))
    perm = multi_key_argsort(xp, sort_cols + [vnull, vkey], capacity)
    live_s = live[perm]
    keep_s = keep[perm]
    # recompute segments over the value-sorted order
    change = xp.zeros(capacity, bool)
    for c0 in sort_cols:
        c = c0[perm]
        shifted = xp.concatenate([c[:1], c[:-1]])
        change = change | (c != shifted)
    if _is_np(xp):
        change = change.copy()
        change[0] = True
    else:
        change = change.at[0].set(True)
    is_start = change & live_s
    seg_ids = xp.cumsum(is_start.astype(np.int64)) - 1
    seg_ids = xp.where(live_s, seg_ids, np.int64(capacity - 1))
    n_valid = segment_reduce(xp, keep_s.astype(np.int64), seg_ids,
                             capacity, "sum")
    ck = xp.cumsum(keep_s.astype(np.int64))
    seg_base = segment_reduce(xp, xp.where(keep_s, ck - 1,
                                           np.int64(1 << 62)),
                              seg_ids, capacity, "min")
    pos = ck - 1 - seg_base[seg_ids]
    got = n_valid > 0
    vdata_s = vdata[perm]
    out = {}
    for f, name in slots:
        target = xp.floor(np.float64(f.percentage)
                          * (n_valid - 1).astype(np.float64)
                          ).astype(np.int64)
        win = keep_s & (pos == target[seg_ids])
        # max over exactly-one-winner IS the gather; empty groups -> NULL
        masked = xp.where(win, vdata_s, np.asarray(ident, vdata.dtype))
        red = segment_reduce(xp, masked, seg_ids, capacity, "max")
        dt = f.data_type(ctx.batch.schema)
        data = red.astype(np.bool_) if np.dtype(dt.np_dtype) == np.bool_ \
            else red.astype(dt.np_dtype)
        out[name] = ColumnVector(data, dt, got, v.dictionary)
    return out


def _collect_into_arrays(xp, ctx, func, perm, sort_cols, seg_ids, is_start,
                         group_pos, live_s, capacity: int) -> ColumnVector:
    """collect_list/collect_set inside the sort-based group path: scatter
    each group's (optionally deduplicated) values into a fixed-width
    ``(groups, Lmax)`` array — position-within-segment is the column, a
    trash row swallows dead/overflow/NULL slots.  The static bound comes
    from ``spark.tpu.collect.maxArrayLen``."""
    from . import config as C
    dt = func.data_type(ctx.batch.schema)
    ed = dt.element_type
    sent = dt.element_sentinel()
    lmax = C.COLLECT_MAX_LEN.default
    try:
        from .sql.session import SparkSession
        s = SparkSession.getActiveSession()
        if s is not None:
            lmax = s.conf.get(C.COLLECT_MAX_LEN)
    except Exception:
        pass

    v = ctx.broadcast(func.children[0].eval(ctx))
    if func.distinct_elements:
        # per-slot re-sort including the value: equal values in a group
        # become adjacent so first-occurrence positions dedupe them
        vdata = v.data
        if (np.asarray(vdata).dtype if _is_np(xp) else vdata.dtype) \
                == np.bool_:
            vdata = vdata.astype(np.int8)
        vnull = xp.zeros(capacity, np.int8) if v.valid is None else \
            xp.where(v.valid, np.int8(0), np.int8(1))
        perm = multi_key_argsort(xp, sort_cols + [vnull, vdata], capacity)
        live_s = ctx.batch.row_valid_or_true()[perm]
        if is_start is not None:
            change = xp.zeros(capacity, bool)
            for c in [c0[perm] for c0 in sort_cols]:
                shifted = xp.concatenate([c[:1], c[:-1]])
                change = change | (c != shifted)
            if _is_np(xp):
                change = change.copy()
                change[0] = True
            else:
                change = change.at[0].set(True)
            is_start = change & live_s
            seg_ids = xp.cumsum(is_start.astype(np.int64)) - 1
            seg_ids = xp.where(live_s, seg_ids, np.int64(capacity - 1))

    value_s = v.data[perm]
    valid_s = None if v.valid is None else v.valid[perm]
    keep = live_s if valid_s is None else (live_s & valid_s)
    if func.distinct_elements:
        prev_v = xp.concatenate([value_s[:1], value_s[:-1]])
        prev_seg = xp.concatenate([seg_ids[:1] - 1, seg_ids[:-1]])
        first = (value_s != prev_v) | (seg_ids != prev_seg)
        keep = keep & first
    # position among KEPT rows of the same segment (cumsum minus the
    # segment's running total at its start)
    ck = xp.cumsum(keep.astype(np.int64))
    seg_base = segment_reduce(xp, xp.where(keep, ck - 1, np.int64(1 << 62)),
                              seg_ids, capacity, "min")
    pos = ck - 1 - seg_base[seg_ids]
    row = xp.where(keep & (pos >= 0) & (pos < lmax), seg_ids,
                   np.int64(capacity))
    col = xp.clip(pos, 0, lmax - 1)
    np_ed = ed.np_dtype
    if _is_np(xp):
        out = np.full((capacity + 1, lmax), sent, np_ed)
        out[np.asarray(row), np.asarray(col)] = np.asarray(value_s
                                                           ).astype(np_ed)
    else:
        out = xp.full((capacity + 1, lmax), sent, np_ed)
        out = out.at[row, col].set(value_s.astype(np_ed))
    return ColumnVector(out[:capacity], dt, None, v.dictionary)


def _scatter_starts(xp, sorted_data: Array, seg_ids: Array, is_start: Array,
                    capacity: int) -> Array:
    """out[g] = sorted_data[first row of segment g] (scatter at starts)."""
    if _is_np(xp):
        out = np.zeros(capacity, dtype=np.asarray(sorted_data).dtype)
        idx = np.asarray(seg_ids)[np.asarray(is_start)]
        out[idx] = np.asarray(sorted_data)[np.asarray(is_start)]
        return out
    target = xp.where(is_start, seg_ids, np.int64(capacity))  # capacity = drop
    out = xp.zeros(capacity, dtype=sorted_data.dtype)
    return out.at[target].set(sorted_data, mode="drop")


# ---------------------------------------------------------------------------
# MXU grouped aggregation (the BytesToBytesMap replacement that actually
# fits the hardware: aggregation as matrix multiplication)
# ---------------------------------------------------------------------------
#
# Spark's fast hash aggregate is a scatter-heavy open-addressing map
# (`unsafe/map/BytesToBytesMap.java:66`).  Scatters are the worst primitive
# on a TPU; matmuls are the best.  This path computes
#
#     sums[b, p] = Σ_rows  one_hot(bucket[row], B) · plane[row, p]
#
# on the MXU, where the planes are 8-bit limbs of the (offset-shifted)
# values plus count masks.  Per-tile f32 accumulations of ≤2048 limbs are
# exact (< 2^19 < 2^24); cross-tile accumulation is int64; limb
# recombination is mod-2^64 two's-complement — so integer sums are
# BIT-EXACT, including overflow wraparound, matching Java long semantics.
#
# Buckets come from composite key codes (key - min, mixed-radix over
# multiple keys, NULL = slot 0).  A runtime `lax.cond` checks that the key
# ranges fit the static bucket capacity and otherwise falls back to the
# sort-based path, so the operator is total.

_MXU_TILE = 2048


def _integral_key(dt: T.DataType) -> bool:
    return (dt.is_integral or isinstance(dt, (T.BooleanType, T.DateType,
                                              T.TimestampType, T.DecimalType))
            or dt.is_string)  # strings group by dictionary code


def _mxu_applicable(schema: T.StructType, key_exprs, agg_slots) -> bool:
    from .aggregates import Avg, Count, CountStar, Sum
    try:
        for k in key_exprs:
            if not _integral_key(k.data_type(schema)):
                return False
        for f, _ in agg_slots:
            if getattr(f, "is_distinct", False):
                return False
            if isinstance(f, (Count, CountStar)):
                continue
            if isinstance(f, (Sum, Avg)):
                src = f.children[0].data_type(schema)
                if src.is_integral or isinstance(src, (T.BooleanType,
                                                       T.DecimalType)):
                    continue
                return False
            return False
    except Exception:
        return False
    return True


def _limb_plan(np_dtype) -> Tuple[int, int]:
    """(n_limbs, offset) for a value dtype: offset shifts the value into
    [0, 2^(8·n_limbs)) so limbs are unsigned; int64 uses the full width
    (offset 2^63 ≡ sign-bit flip, mod-2^64 arithmetic)."""
    dt = np.dtype(np_dtype)  # bool inputs are cast to int8 by the caller
    bits = dt.itemsize * 8
    return dt.itemsize, 1 << (bits - 1)


# TPU VPUs have no 64-bit lanes — XLA emulates every int64 op with a
# multi-op 32-bit expansion, which made the O(n) prep (bucket codes, limb
# extraction) dominate the whole aggregation.  The helpers below keep all
# O(n) arithmetic in native 32-bit: int64 columns are split into (lo, hi)
# uint32 halves by bitcast (XLA defines minor index 0 = least-significant
# word), min/max are two-pass lexicographic reductions, and in-range codes
# come from low-half arithmetic alone (exact whenever the range fits the
# bucket table — `fits` guards it; the sort-based branch owns the rest).

def _i64_halves(xp, data):
    """(lo, hi) uint32 halves of ``data`` sign-extended to int64."""
    import jax
    import jax.numpy as jnp
    if data.dtype.itemsize == 8:
        pair = jax.lax.bitcast_convert_type(data.astype(jnp.int64),
                                            jnp.uint32)
        return pair[..., 0], pair[..., 1]
    w = data.astype(jnp.int32)
    return w.astype(jnp.uint32), (w >> 31).astype(jnp.uint32)


def _masked_minmax64(xp, lo, hi, mask):
    """(kmin_i64, kmax_i64, kmin_lo_u32) over rows where mask, via int32
    lexicographic (hi signed, lo unsigned) two-pass reductions.  Empty mask
    yields (INT64_MAX, INT64_MIN, UINT32_MAX) — the sort-branch sentinels."""
    import jax.numpy as jnp
    hi_s = hi.astype(jnp.int32)
    min_hi = xp.min(xp.where(mask, hi_s, np.int32(np.iinfo(np.int32).max)))
    min_lo = xp.min(xp.where(mask & (hi_s == min_hi), lo,
                             np.uint32(0xFFFFFFFF)))
    max_hi = xp.max(xp.where(mask, hi_s, np.int32(np.iinfo(np.int32).min)))
    max_lo = xp.max(xp.where(mask & (hi_s == max_hi), lo, np.uint32(0)))

    def comb(h, l):
        return (h.astype(jnp.int64) << np.int64(32)) | l.astype(jnp.int64)

    return comb(min_hi, min_lo), comb(max_hi, max_lo), min_lo


def _mxu_grouped_aggregate(xp, batch, key_exprs, agg_slots, bucket_cap):
    import jax
    import jax.numpy as jnp
    from . import pallas_agg
    from .aggregates import Avg, Count, CountStar, Sum

    ctx = EvalContext(batch, xp)
    capacity = batch.capacity
    live = xp.broadcast_to(batch.row_valid_or_true(), (capacity,))
    schema = batch.schema

    B = int(min(bucket_cap, capacity))
    L = int(min(_MXU_TILE, capacity))
    n_pad = ((capacity + L - 1) // L) * L

    # ---- composite bucket codes (mixed radix over keys, NULL = 0) -------
    # All O(n) arithmetic is 32-bit native (see _i64_halves): codes come
    # from low-half differences, exact whenever `fits` holds; the slow
    # branch owns every other execution, so garbage codes are harmless.
    key_vals: List[ExprValue] = [ctx.broadcast(k.eval(ctx)) for k in key_exprs]
    key_dts = [k.data_type(schema) for k in key_exprs]
    codes = []          # per-key (code32 in [0, r), r32, kmin_i64, nullable)
    prod = xp.ones((), np.float64)   # overflow-safe fit check in f64
    for v in key_vals:
        data = v.data
        if data.dtype == np.bool_:
            data = data.astype(np.int8)
        lo, hi = _i64_halves(xp, data)
        mask = live if v.valid is None else (live & v.valid)
        kmin, kmax, kmin_lo = _masked_minmax64(xp, lo, hi, mask)
        # the authoritative range estimate is f64 (int64 spans can exceed
        # any 32-bit arithmetic); only trusted when `fits` proves it small
        rangef = xp.maximum(kmax.astype(np.float64) - kmin.astype(np.float64)
                            + 1.0, 0.0)
        r32 = xp.clip(rangef, 0.0, np.float64(B + 2)).astype(np.int32)
        diff = (lo - kmin_lo).astype(np.int32)   # mod-2^32; exact iff fits
        nullable = v.valid is not None
        if nullable:
            code = xp.where(mask, diff + 1, 0)
            r32 = r32 + 1
            prod = prod * (rangef + 1.0)
        else:
            code = diff
            r32 = xp.maximum(r32, 1)
            prod = prod * xp.maximum(rangef, 1.0)
        codes.append((code, r32, kmin, nullable))

    bucket = xp.zeros(capacity, np.int32)
    for code, r32, _, _ in codes:
        bucket = bucket * r32 + code   # wraps only when not fits
    fits = prod <= np.float64(B)
    bucket32 = xp.clip(bucket, 0, B - 1)

    def fast_branch(_):
        # ---- plane assembly (fast branch only: fallback executions must
        # not pay the O(n·P) limb extraction) ------------------------------
        # plane 0: live-row count; per Sum/Avg: limb planes + own count
        # plane; per Count: count plane.  All bf16 {0..255}-valued.
        planes: List[Array] = [live.astype(jnp.bfloat16)]
        agg_plane_info = []  # (func, name, kind, first_plane, offset, n_limbs)
        for func, name in agg_slots:
            if isinstance(func, CountStar):
                agg_plane_info.append((func, name, "countstar", None, 0, 0))
                continue
            v = ctx.broadcast(func.children[0].eval(ctx))
            m = live if v.valid is None else (live & v.valid)
            if isinstance(func, Count):
                start = len(planes)
                planes.append(m.astype(jnp.bfloat16))
                agg_plane_info.append((func, name, "count", start, 0, 0))
                continue
            # Sum / Avg over integral input
            data = v.data
            if data.dtype == np.bool_:
                data = data.astype(np.int8)
            n_limbs, offset = _limb_plan(data.dtype)
            # 32-bit-native limb extraction: the +offset sign shift is a
            # top-bit flip for 8-byte values (no carry: 2^63 IS the top
            # bit) and a mod-2^32 low-word add for narrower ones (only the
            # low 8*n_limbs bits are read, which the wrap cannot touch)
            lo, hi = _i64_halves(xp, data)
            if n_limbs == 8:
                words = (lo, hi ^ np.uint32(0x80000000))
            else:
                words = (lo + np.uint32(offset),)
            start = len(planes)
            for i in range(n_limbs):
                w = words[i // 4]
                limb = (w >> np.uint32(8 * (i % 4))) & np.uint32(0xFF)
                limb = xp.where(m, limb, np.uint32(0))
                planes.append(limb.astype(jnp.bfloat16))
            planes.append(m.astype(jnp.bfloat16))   # per-agg count
            agg_plane_info.append((func, name, "sum", start, offset, n_limbs))

        P = len(planes)
        plane_mat = xp.stack(planes, axis=-1)                # (n, P)

        if pallas_agg.supported(B) and _on_tpu_device():
            # Pallas accumulate: one-hot tiles built in VMEM, (B, P) int32
            # accumulator in scratch, bucket chunks beyond the runtime key
            # range skipped — HBM traffic is one pass over the planes
            n_active = pallas_agg.n_active_chunks(xp, prod, B)
            tot = pallas_agg.grouped_accumulate(bucket32, plane_mat,
                                                n_active, B)
        else:
            bucket_pad = bucket32
            if n_pad != capacity:
                plane_mat = xp.concatenate(
                    [plane_mat, xp.zeros((n_pad - capacity, P), jnp.bfloat16)])
                bucket_pad = xp.concatenate(
                    [bucket32, xp.zeros(n_pad - capacity, np.int32)])
            T_tiles = n_pad // L

            bb = bucket_pad.reshape(T_tiles, L)
            pp = plane_mat.reshape(T_tiles, L, P)
            oh = jax.nn.one_hot(bb, B, dtype=jnp.bfloat16)        # (T, L, B)
            per_tile = jnp.einsum("tlb,tlp->tbp", oh, pp,
                                  preferred_element_type=jnp.float32)
            # exact integer accumulation across tiles; int32 is enough while
            # total counts/limb-sums stay < 2^31 (n·255), halving HBM traffic
            acc_dt = jnp.int32 if n_pad * 255 < (1 << 31) else jnp.int64
            tot = per_tile.astype(acc_dt).sum(0).astype(jnp.int64)  # (B, P)
        live_count = tot[:, 0]
        grow = live_count > 0                                 # real groups

        out_datas: List[Array] = []
        out_valids: List[Array] = []
        # decode keys from bucket index (mixed radix, most-significant first)
        rem = xp.arange(B, dtype=np.int64)
        strides = []
        s = xp.ones((), np.int64)
        for _, r, _, _ in reversed(codes):
            strides.append(s)
            s = s * r
        strides.reverse()
        for (code, r, kmin, nullable), stride, v, dt in zip(
                codes, strides, key_vals, key_dts):
            digit = (rem // stride) % xp.maximum(r, 1)
            if nullable:
                kdata = kmin + digit - 1
                kvalid = grow & (digit > 0)
            else:
                kdata = kmin + digit
                kvalid = grow
            np_dt = dt.np_dtype
            out_datas.append(kdata.astype(np_dt))
            out_valids.append(kvalid)

        for func, name, kind, start, offset, n_limbs in agg_plane_info:
            if kind == "countstar":
                out_datas.append(live_count)
                out_valids.append(grow)
                continue
            if kind == "count":
                out_datas.append(tot[:, start])
                out_valids.append(grow)
                continue
            cnt = tot[:, start + n_limbs]
            acc = xp.zeros(B, jnp.uint64)
            for i in range(n_limbs):
                acc = acc + (tot[:, start + i].astype(jnp.uint64)
                             << jnp.uint64(8 * i))
            total = (acc - cnt.astype(jnp.uint64) * jnp.uint64(offset)
                     ).astype(jnp.int64)
            if isinstance(func, Avg):
                src = func.children[0].data_type(schema)
                f = total.astype(np.float64)
                if isinstance(src, T.DecimalType):
                    f = f / (10 ** src.scale)
                safe = xp.where(cnt > 0, cnt, 1)
                out_datas.append(f / safe)
            else:
                out_dt = func.data_type(schema).np_dtype
                out_datas.append(total.astype(out_dt))
            out_valids.append(grow & (cnt > 0))

        def pad(a):
            if B == capacity:
                return a
            fill = xp.zeros(capacity - B, a.dtype)
            return xp.concatenate([a, fill])

        return (tuple(pad(d) for d in out_datas),
                tuple(pad(v) for v in out_valids),
                pad(grow))

    def slow_branch(_):
        cb = _sorted_grouped_aggregate(xp, batch, key_exprs, agg_slots)
        datas = tuple(v.data for v in cb.vectors)
        valids = tuple(
            xp.broadcast_to(v.valid, (capacity,)) if v.valid is not None
            else xp.ones(capacity, bool) for v in cb.vectors)
        return datas, valids, xp.broadcast_to(cb.row_valid_or_true(),
                                              (capacity,))

    datas, valids, row_valid = jax.lax.cond(fits, fast_branch, slow_branch,
                                            None)

    # ---- assemble (names/dtypes/dictionaries are host-static) -----------
    out_names: List[str] = []
    out_vectors: List[ColumnVector] = []
    i = 0
    for k, v, dt in zip(key_exprs, key_vals, key_dts):
        out_names.append(k.name)
        out_vectors.append(ColumnVector(datas[i], dt, valids[i], v.dictionary))
        i += 1
    for func, name in agg_slots:
        dt = func.data_type(schema)
        out_names.append(name)
        out_vectors.append(ColumnVector(datas[i], dt, valids[i],
                                        func.output_dictionary(ctx)))
        i += 1
    return ColumnBatch(out_names, out_vectors, row_valid, capacity)


# ---------------------------------------------------------------------------
# distinct / union
# ---------------------------------------------------------------------------

def distinct(xp, batch: ColumnBatch) -> ColumnBatch:
    """Deduplicate live rows (group by all columns, keep firsts)."""
    from .expressions import Col
    keys = [Col(n) for n in batch.names]
    out = grouped_aggregate(xp, batch, keys, [])
    return out


def remap_codes(xp, codes, table):
    """Gather dictionary codes into a merged code space (jittable).

    ``table[old_code] -> new_code`` must be monotone — engine
    dictionaries are sorted, so ``merge_dictionaries`` remaps are —
    which keeps sorted runs sorted across the remap (the range-merge
    path depends on this).  Sentinel-preserving, unlike a clipping
    gather: codes at or above ``len(table)`` (the min-buffer identity
    INT32_MAX on a live all-NULL aggregate row) stay INT32_MAX, and
    negative codes (NULL -1, the max-buffer / first-value identity
    INT32_MIN) pass through unchanged, so a reduction identity is still
    an identity after the hop instead of aliasing onto a real word."""
    codes = xp.asarray(codes)
    table = xp.asarray(table)
    dt = codes.dtype
    n = int(table.shape[0])
    if n:
        gathered = table[xp.clip(codes, 0, n - 1)].astype(dt)
    else:
        gathered = codes
    hi = np.asarray(np.iinfo(np.int32).max, dt)
    out = xp.where(codes >= n, hi, gathered)
    return xp.where(codes < 0, codes, out).astype(dt)


def union_all(batches: Sequence[ColumnBatch]) -> ColumnBatch:
    """Concatenate batches (host-side shape change; capacity = sum).

    String columns re-encode onto a merged dictionary via
    ``remap_codes``; identical dictionaries (the post-exchange common
    case — the hop already unified code spaces) skip the remap.
    """
    assert batches
    names = batches[0].names
    capacity = sum(b.capacity for b in batches)
    vectors: List[ColumnVector] = []
    for ci, name in enumerate(names):
        vecs = [b.vectors[ci] for b in batches]
        dtype = vecs[0].dtype
        dicts = [v.dictionary for v in vecs]
        runs = [unmaterialized_runs(v) for v in vecs]
        if (any(r is not None for r in runs)
                and all((r.valid is None and r.capacity == b.capacity)
                        if r is not None else v.valid is None
                        for r, v, b in zip(runs, vecs, batches))
                and len({d or () for d in dicts}) == 1):
            # at least one piece is still run-encoded over one shared
            # code space: concatenate the run TABLES and stay lazy
            # (adjacent equal values across a seam are two runs — still
            # a valid table).  A DENSE sibling piece — typically the
            # reducer's own map output, which short-circuits the wire
            # and so was never run-detected — is re-encoded here IF it
            # compresses (one vectorized diff); a piece that doesn't
            # falls through to the dense concat, inflating the encoded
            # pieces exactly as before
            tables = []
            for r, v, b in zip(runs, vecs, batches):
                if r is not None:
                    tables.append(
                        (np.asarray(r.run_values, dtype.np_dtype),
                         r.run_lengths))
                    continue
                vals, lens = rle_encode(np.asarray(v.data,
                                                   dtype.np_dtype))
                if len(vals) * 2 > b.capacity:
                    tables = None
                    break
                tables.append((vals, lens))
            if tables is not None:
                rvals = np.concatenate([t[0] for t in tables])
                rlens = np.concatenate([t[1] for t in tables])
                vectors.append(RunColumnVector(rvals, rlens, dtype, None,
                                               dicts[0]))
                continue
        if dtype.is_string or isinstance(dtype, T.BinaryType):
            if len({d or () for d in dicts}) == 1:
                data = np.concatenate([np.asarray(v.data) for v in vecs])
                dictionary = dicts[0] or ()
            else:
                merged = dicts[0] or ()
                remaps = [None] * len(vecs)
                for i in range(1, len(vecs)):
                    merged, ra, rb = merge_dictionaries(merged, dicts[i] or ())
                    # ra remaps everything merged so far; fold into
                    # earlier remaps
                    for j in range(i):
                        remaps[j] = ra if remaps[j] is None else ra[remaps[j]]
                    remaps[i] = rb
                datas = []
                for v, rm in zip(vecs, remaps):
                    d = np.asarray(v.data)
                    datas.append(remap_codes(np, d, rm)
                                 if rm is not None else d)
                data = np.concatenate(datas)
                dictionary = merged
        else:
            data = np.concatenate([np.asarray(v.data, dtype.np_dtype) for v in vecs])
            dictionary = None
        valids = [v.valid for v in vecs]
        if any(vl is not None for vl in valids):
            valid = np.concatenate([
                np.asarray(vl) if vl is not None else np.ones(b.capacity, bool)
                for vl, b in zip(valids, batches)])
        else:
            valid = None
        vectors.append(ColumnVector(data, dtype, valid, dictionary))
    rv = np.concatenate([np.asarray(b.row_valid_or_true()) for b in batches])
    return ColumnBatch(names, vectors, rv, capacity)


def align_string_columns(a: ColumnBatch, a_col: str, b: ColumnBatch, b_col: str
                         ) -> Tuple[ColumnBatch, ColumnBatch]:
    """Re-encode two string columns onto a shared dictionary (host-side prep
    before joins/set-ops compare them on device)."""
    va, vb = a.column(a_col), b.column(b_col)
    if va.dictionary == vb.dictionary:
        return a, b
    merged, ra, rb = merge_dictionaries(va.dictionary or (), vb.dictionary or ())

    def remap(batch, name, vec, rm):
        new = remap_codes(np, np.asarray(vec.data), rm)
        i = batch.names.index(name)
        vecs = list(batch.vectors)
        vecs[i] = ColumnVector(new.astype(np.int32), vec.dtype, vec.valid, merged)
        return ColumnBatch(batch.names, vecs, batch.row_valid, batch.capacity)

    return remap(a, a_col, va, ra), remap(b, b_col, vb, rb)
