"""Aggregate functions, decomposed into segment-reducible buffers.

The analog of ``sql/catalyst/.../expressions/aggregate/`` redesigned for
TPU: every aggregate is expressed as a small set of BUFFERS, each reduced
with one of {sum, min, max} — the only reductions we ever run on device
(as ``segment_sum``-style ops locally, ``psum``-style collectives across
the mesh).  This decomposition *is* the partial/final aggregation split of
``AggUtils.scala``: partial agg materializes buffer columns, re-aggregation
after an exchange reduces the same buffers again (sum of sums, min of mins),
and ``finish`` runs only at the final step.  It gives distributed merge,
spill-merge, and streaming-state merge one shared code path.
"""

from __future__ import annotations

from typing import Any, List, NamedTuple, Tuple

import numpy as np

from . import types as T
from .expressions import AnalysisException, EvalContext, Expression, ExprValue, and_valid

__all__ = [
    "AggregateFunction", "BufferSpec", "Sum", "Count", "CountStar", "Avg",
    "Min", "Max", "First", "Last", "VarianceBase", "VarSamp", "VarPop",
    "StddevSamp", "StddevPop", "CountDistinct", "SumDistinct",
    "AggregateExpression", "is_aggregate",
]


class BufferSpec(NamedTuple):
    """One reducible buffer: data to reduce, reduction kind, and the value
    used for rows that do not contribute (the reduction identity)."""

    data: Any            # array (capacity,)
    kind: str            # 'sum' | 'min' | 'max'
    np_dtype: np.dtype   # buffer storage dtype


def _min_ident(dt):
    dt = np.dtype(dt)
    if dt == np.bool_:
        return True
    return np.inf if np.issubdtype(dt, np.floating) else np.iinfo(dt).max


def _max_ident(dt):
    dt = np.dtype(dt)
    if dt == np.bool_:
        return False
    return -np.inf if np.issubdtype(dt, np.floating) else np.iinfo(dt).min


IDENTITY = {
    "sum": lambda dt: np.zeros((), dt).item() if np.issubdtype(dt, np.floating) else 0,
    "min": _min_ident,
    "max": _max_ident,
}


class AggregateFunction(Expression):
    """Base: children are input expressions; eval() is forbidden (aggregates
    are consumed by the Aggregate operator, reference
    ``DeclarativeAggregate`` vs row-at-a-time ``ImperativeAggregate``)."""

    def __init__(self, *children: Expression):
        self.children = tuple(children)

    def eval(self, ctx: EvalContext) -> ExprValue:
        raise AnalysisException(
            f"aggregate function {self!r} cannot be evaluated row-wise; "
            "use it under groupBy().agg(...)")

    # -- the buffer contract ---------------------------------------------
    def num_buffers(self) -> int:
        raise NotImplementedError

    def make_buffers(self, ctx: EvalContext, contribute) -> List[BufferSpec]:
        """Per-row buffer contributions.  ``contribute`` is the boolean mask
        of rows that exist (row_valid AND any operator predicate); each
        buffer must hold its reduction identity where a row does not
        contribute (or its input is NULL)."""
        raise NotImplementedError

    def finish(self, xp, buffers: List[Any]) -> ExprValue:
        """Combine reduced buffers into the output column value."""
        raise NotImplementedError

    def output_dictionary(self, ctx: EvalContext):
        """Dictionary of the output column (min/max/first of strings)."""
        return None

    def _input(self, ctx: EvalContext, contribute) -> Tuple[Any, Any]:
        """Evaluate the single input expr; returns (data, valid&contribute)."""
        v = self.children[0].eval(ctx)
        xp = ctx.xp
        valid = and_valid(xp, v.valid, contribute)
        if valid is None:
            valid = xp.ones(ctx.capacity, dtype=bool)
        data = v.data
        if getattr(data, "shape", ()) == ():
            data = xp.broadcast_to(data, (ctx.capacity,))
        valid = xp.broadcast_to(valid, (ctx.capacity,))
        return data, valid

    def _masked(self, xp, data, valid, kind: str, np_dtype) -> BufferSpec:
        ident = IDENTITY[kind](np_dtype)
        return BufferSpec(
            xp.where(valid, data.astype(np_dtype), np.asarray(ident, np_dtype)),
            kind, np.dtype(np_dtype))


class Sum(AggregateFunction):
    """sum(x): NULL if no non-null input (Sum.scala)."""

    def data_type(self, schema):
        dt = self.children[0].data_type(schema)
        if isinstance(dt, T.DecimalType):
            return T.DecimalType(T.DecimalType.MAX_PRECISION, dt.scale)
        if dt.is_integral or isinstance(dt, T.BooleanType):
            return T.int64
        return T.float64

    def num_buffers(self):
        return 2

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        out_dt = self.data_type(ctx.batch.schema).np_dtype
        return [self._masked(xp, data, valid, "sum", out_dt),
                BufferSpec(valid.astype(np.int64), "sum", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        total, cnt = buffers
        return ExprValue(total, cnt > 0)

    def __repr__(self):
        return f"sum({self.children[0]!r})"


class Count(AggregateFunction):
    """count(x): number of non-null inputs; never NULL."""

    def data_type(self, schema):
        return T.int64

    def num_buffers(self):
        return 1

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        _, valid = self._input(ctx, contribute)
        return [BufferSpec(valid.astype(np.int64), "sum", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        return ExprValue(buffers[0], None)

    def __repr__(self):
        return f"count({self.children[0]!r})"


class CountStar(AggregateFunction):
    """count(*): counts rows regardless of nulls."""

    def __init__(self):
        super().__init__()

    def data_type(self, schema):
        return T.int64

    def num_buffers(self):
        return 1

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        c = contribute if contribute is not None else xp.ones(ctx.capacity, bool)
        return [BufferSpec(c.astype(np.int64), "sum", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        return ExprValue(buffers[0], None)

    def __repr__(self):
        return "count(1)"


class Avg(AggregateFunction):
    def data_type(self, schema):
        return T.float64

    def num_buffers(self):
        return 2

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        src = self.children[0].data_type(ctx.batch.schema)
        fdata = data.astype(np.float64)
        if isinstance(src, T.DecimalType):
            fdata = fdata / (10 ** src.scale)
        return [self._masked(xp, fdata, valid, "sum", np.float64),
                BufferSpec(valid.astype(np.int64), "sum", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        total, cnt = buffers
        safe = xp.where(cnt > 0, cnt, 1)
        return ExprValue(total / safe, cnt > 0)

    def __repr__(self):
        return f"avg({self.children[0]!r})"


class _MinMax(AggregateFunction):
    kind = "min"

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def num_buffers(self):
        return 2

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        dt = self.data_type(ctx.batch.schema).np_dtype
        if dt == np.bool_:
            dt = np.dtype(np.int8)
        return [self._masked(xp, data, valid, self.kind, dt),
                BufferSpec(valid.astype(np.int64), "sum", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        val, cnt = buffers
        return ExprValue(val, cnt > 0)

    def output_dictionary(self, ctx: EvalContext):
        return self.children[0].eval(ctx).dictionary

    def __repr__(self):
        return f"{self.kind}({self.children[0]!r})"


class Min(_MinMax):
    kind = "min"


class Max(_MinMax):
    kind = "max"


class First(AggregateFunction):
    """first(x, ignoreNulls=True): value of x on the first contributing row.

    Implemented order-sensitively via a min-reduction over (row_index) and a
    gather at finish is not expressible as a pure buffer reduce; instead we
    encode (index, value) packed — min over index with the value carried via
    a second min buffer keyed the same way works only when values are
    monotone.  We use the standard trick: reduce min over
    ``index*`` and separately reduce min over ``(index << 1) | bit``? —
    too cute.  Pragmatic choice: min-reduce the row index, then the operator
    gathers the value at that index (needs the pre-reduction batch, which the
    Aggregate operator has).  So First contributes an 'argmin' buffer the
    operator special-cases.
    """

    def __init__(self, child: Expression, ignore_nulls: bool = True):
        super().__init__(child)
        self.ignore_nulls = ignore_nulls

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def num_buffers(self):
        return 1

    ARGREDUCE = "first"

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        if not self.ignore_nulls:
            _, valid = None, xp.broadcast_to(
                contribute if contribute is not None else xp.ones(ctx.capacity, bool),
                (ctx.capacity,))
        idx = xp.arange(ctx.capacity, dtype=np.int64)
        big = np.int64(1 << 62)
        return [BufferSpec(xp.where(valid, idx, big), "min", np.dtype(np.int64))]

    def finish(self, xp, buffers):
        raise AnalysisException("First/Last finish requires operator gather")

    def output_dictionary(self, ctx: EvalContext):
        return self.children[0].eval(ctx).dictionary

    def __repr__(self):
        return f"first({self.children[0]!r})"


class Last(First):
    ARGREDUCE = "last"

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        if not self.ignore_nulls:
            valid = xp.broadcast_to(
                contribute if contribute is not None else xp.ones(ctx.capacity, bool),
                (ctx.capacity,))
        idx = xp.arange(ctx.capacity, dtype=np.int64)
        return [BufferSpec(xp.where(valid, idx, np.int64(-1)), "max", np.dtype(np.int64))]

    def __repr__(self):
        return f"last({self.children[0]!r})"


class VarianceBase(AggregateFunction):
    """var/stddev via (count, sum, sum of squares) buffers.

    The reference uses Welford-style central moments
    (``aggregate/CentralMomentAgg.scala``); sum-of-squares buffers are
    mergeable with plain sums, which Welford deltas are not, and float64
    accumulation over HBM-sized batches is acceptable precision-wise.
    """

    ddof = 1

    def data_type(self, schema):
        return T.float64

    def num_buffers(self):
        return 3

    def make_buffers(self, ctx, contribute):
        xp = ctx.xp
        data, valid = self._input(ctx, contribute)
        f = data.astype(np.float64)
        return [BufferSpec(valid.astype(np.int64), "sum", np.dtype(np.int64)),
                self._masked(xp, f, valid, "sum", np.float64),
                self._masked(xp, f * f, valid, "sum", np.float64)]

    def _variance(self, xp, buffers):
        n, s, s2 = buffers
        nf = n.astype(np.float64)
        safe_n = xp.where(n > self.ddof, nf, 1.0)
        mean = s / xp.where(n > 0, nf, 1.0)
        var = xp.maximum(s2 - nf * mean * mean, 0.0) / xp.maximum(safe_n - self.ddof, 1.0)
        return var, n > self.ddof

    def finish(self, xp, buffers):
        var, valid = self._variance(xp, buffers)
        return ExprValue(var, valid)


class VarSamp(VarianceBase):
    ddof = 1

    def __repr__(self):
        return f"var_samp({self.children[0]!r})"


class VarPop(VarianceBase):
    ddof = 0

    def __repr__(self):
        return f"var_pop({self.children[0]!r})"


class StddevSamp(VarianceBase):
    ddof = 1

    def finish(self, xp, buffers):
        var, valid = self._variance(xp, buffers)
        return ExprValue(xp.sqrt(var), valid)

    def __repr__(self):
        return f"stddev_samp({self.children[0]!r})"


class StddevPop(StddevSamp):
    ddof = 0

    def __repr__(self):
        return f"stddev_pop({self.children[0]!r})"


class CountDistinct(Count):
    """count(DISTINCT x): planned as a two-level aggregation — the analyzer
    rewrites Aggregate[keys][count_distinct(x)] into
    Aggregate[keys][count(x)] over Aggregate[keys+x][] (the expansion of
    ``RewriteDistinctAggregates.scala`` restricted to one distinct column).
    """

    is_distinct = True

    def __repr__(self):
        return f"count(DISTINCT {self.children[0]!r})"


class SumDistinct(Sum):
    is_distinct = True

    def __repr__(self):
        return f"sum(DISTINCT {self.children[0]!r})"


#: merge function per buffer reduction kind — the single definition shared
#: by distributed final-agg, streaming state merge, and multi-batch folds
MERGE_BY_KIND = {"sum": Sum, "min": Min, "max": Max}


def buffer_kinds(func: AggregateFunction, child_schema) -> List[str]:
    """Reduction kind of each buffer, derived by probing make_buffers on an
    empty batch — stays correct by construction when buffer layouts change."""
    from .columnar import ColumnBatch
    probe = ColumnBatch.empty(child_schema)
    ctx = EvalContext(probe, np)
    live = np.zeros(probe.capacity, bool)
    return [s.kind for s in func.make_buffers(ctx, live)]


class AggregateExpression(NamedTuple):
    """A named aggregate output slot in an Aggregate operator."""

    func: AggregateFunction
    name: str


def is_aggregate(e: Expression) -> bool:
    if isinstance(e, AggregateFunction):
        return True
    return any(is_aggregate(c) for c in e.children)


class CollectList(AggregateFunction):
    """collect_list(x): group elements as an array, in sort order (the
    reference's order is nondeterministic too).  NULL inputs are skipped.
    Output arrays are capped at ``spark.tpu.collect.maxArrayLen`` elements
    (static shapes require a bound); overflow truncates — deviation,
    raise the cap for bigger groups."""

    is_collect = True
    distinct_elements = False

    def data_type(self, schema):
        return T.ArrayType(self.children[0].data_type(schema))

    def num_buffers(self):
        return 0

    def make_buffers(self, ctx, contribute):
        raise AnalysisException(
            "collect_list/collect_set only run on the sort-based "
            "aggregation path")

    def __repr__(self):
        return f"collect_list({self.children[0]!r})"


class CollectSet(CollectList):
    """collect_set(x): distinct group elements as an array."""

    distinct_elements = True

    def __repr__(self):
        return f"collect_set({self.children[0]!r})"


class PercentileApprox(AggregateFunction):
    """percentile_approx(x, p): the reference's ApproximatePercentile
    (t-digest sketch); this engine computes the EXACT per-group
    percentile — the sort-based group path already has sorted values in
    hand, so exactness is free (a better answer than the contract asks).
    Interpolation is nearest-rank at floor(p * (n-1)), matching the
    accuracy=1 behavior."""

    is_percentile = True

    def __init__(self, child, percentage: float):
        super().__init__(child)
        if not (0.0 <= float(percentage) <= 1.0):
            raise AnalysisException(
                f"percentile must be in [0, 1], got {percentage}")
        self.percentage = float(percentage)

    def map_children(self, fn):
        return PercentileApprox(fn(self.children[0]), self.percentage)

    def data_type(self, schema):
        return self.children[0].data_type(schema)

    def num_buffers(self):
        return 0

    def make_buffers(self, ctx, contribute):
        raise AnalysisException(
            "percentile_approx only runs on the sort-based aggregation "
            "path")

    def __repr__(self):
        return f"percentile_approx({self.children[0]!r}, {self.percentage})"
