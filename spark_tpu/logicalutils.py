"""Tiny shared helpers between the Column API and logical plans (avoids an
import cycle between sql.column and sql.logical)."""

from __future__ import annotations

from typing import Optional

from .expressions import Expression


class _SortOrderHandle:
    """Carried by Column.asc()/desc() until the Sort node is built."""

    def __init__(self, expr: Expression, ascending: bool, nulls_first: Optional[bool]):
        self.expr = expr
        self.ascending = ascending
        self.nulls_first = nulls_first


def sort_order(expr: Expression, ascending: bool, nulls_first: Optional[bool]):
    return _SortOrderHandle(expr, ascending, nulls_first)
