"""DB-API-backed relational datasource — the JDBC source analog.

Reference roles covered:
- ``sql/core/src/main/scala/.../datasources/jdbc/JDBCRDD.scala``
  (``scanTable``: pruned column list + pushed WHERE + one partition
  predicate per task);
- ``JDBCRelation.scala`` ``columnPartition`` (stride partitioning of
  ``[lowerBound, upperBound)`` on a numeric partition column, first/last
  partitions open-ended, NULLs in the first);
- ``JdbcUtils.scala`` ``savePartition`` / ``createTable`` (write path:
  schema-derived DDL + batched parameterized INSERTs).

tpu-first divergence: there is no JVM and no JDBC driver manager here.
The wire role is played by DB-API 2.0 (PEP 249) connections — sqlite3
from the stdlib always works; any other installed driver module is
loaded by URL scheme (``postgresql://...`` → ``import postgresql``) or
named explicitly via the ``driver`` option.  Each partition query lands
in one pyarrow table and enters the SAME columnar scan path as every
file format (``io._load_batch``), so pruning, the multibatch streamer
and the stage runner see no difference between a parquet directory and
a database table.

Freshness: unlike file relations (cache keyed by mtimes), database
DATA reads are NEVER cached — a mutable store has no cheap invalidation
token, so every query re-reads (the reference re-runs its JDBC scan per
job for the same reason).  The resolved schema and COUNT(*) planning
stats ARE memoized per relation (and evicted by our own writes): they
play the role of the reference's ANALYZE-gathered statistics, which are
exactly as stale.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from .expressions import AnalysisException

#: subquery alias for query-shaped relations (JDBCRelation quotes its
#: ``query`` option the same way)
_SUBQ = "spark_tpu_subquery"


# ---------------------------------------------------------------------------
# connections
# ---------------------------------------------------------------------------

def _normalize_url(url: str) -> str:
    return url[5:] if url.lower().startswith("jdbc:") else url


def _sqlite_path(url: str) -> str:
    """``sqlite:///abs/path`` / ``sqlite:/abs/path`` / ``sqlite:rel`` →
    filesystem path for sqlite3.connect.  ``:memory:`` is rejected: each
    connect() would open a DISTINCT empty database (this module opens a
    fresh connection per operation), so in-memory writes would always be
    silently lost."""
    rest = url.split(":", 1)[1]
    if rest in (":memory:", "memory:"):
        raise AnalysisException(
            "jdbc:sqlite::memory: is not supported: every read/write "
            "opens its own connection, and an in-memory sqlite database "
            "dies with its connection — use a file-backed database")
    while rest.startswith("//"):
        rest = rest[1:]
    return rest


def connect(url: str, options: Dict[str, str], create: bool = False):
    """Open a DB-API connection for `url`.  Returns (connection,
    paramstyle).  ``create=True`` (write path) lets sqlite bootstrap a
    missing database file; reads of a missing file stay a loud error
    (sqlite3.connect would silently create an empty db and every query
    would report zero rows)."""
    url = _normalize_url(url)
    scheme = url.split(":", 1)[0].lower() if ":" in url else ""
    driver = options.get("driver")
    if driver is None and scheme in ("sqlite", "sqlite3", ""):
        import sqlite3
        path = _sqlite_path(url) if ":" in url else url
        if not create and not os.path.exists(path):
            raise AnalysisException(f"sqlite database not found: {path}")
        return sqlite3.connect(path), "qmark"
    mod_name = driver or scheme
    try:
        mod = __import__(mod_name)
    except ImportError as e:
        raise AnalysisException(
            f"no DB-API driver for jdbc url {url!r}: module {mod_name!r} "
            "is not installed (set the `driver` option to a PEP 249 "
            "module name)") from e
    conn = mod.connect(url)
    return conn, getattr(mod, "paramstyle", "qmark")


# ---------------------------------------------------------------------------
# partitioning (JDBCRelation.columnPartition)
# ---------------------------------------------------------------------------

def partition_predicates(options: Dict[str, str]) -> List[Optional[str]]:
    """One SQL predicate per read partition.

    Explicit ``predicates`` (unit-separator-joined, set by
    ``DataFrameReader.jdbc``) win; else stride partitioning of
    [lowerbound, upperbound) on ``partitioncolumn`` into
    ``numpartitions`` ranges — first/last open-ended so no row outside
    the bounds is lost, NULLs ride the first partition (exactly
    ``JDBCRelation.scala`` ``columnPartition``'s clauses)."""
    preds = options.get("predicates")
    if preds:
        return list(preds.split("\x1f"))
    col = options.get("partitioncolumn")
    n = int(options.get("numpartitions", "1") or 1)
    if not col or n <= 1:
        return [None]
    lo = int(options["lowerbound"])
    hi = int(options["upperbound"])
    if hi <= lo:
        raise AnalysisException(
            f"jdbc upperBound ({hi}) must exceed lowerBound ({lo})")
    stride = max((hi - lo) // n, 1)
    out: List[Optional[str]] = []
    for i in range(n):
        low = lo + i * stride
        up = lo + (i + 1) * stride
        if i == 0:
            out.append(f'"{col}" < {up} OR "{col}" IS NULL')
        elif i == n - 1:
            out.append(f'"{col}" >= {low}')
        else:
            out.append(f'"{col}" >= {low} AND "{col}" < {up}')
    return out


def _pushed_sql(pushed) -> List[str]:
    """Engine pushdown tuples (name, op, value) → SQL conjuncts.

    Only predicates whose SQL semantics provably match the engine's are
    emitted (int comparisons; string EQUALITY — inequality is collation-
    dependent).  The exact Filter stays in the plan either way
    (optimizer.push_scan_filters), so this is a row-reduction hint that
    can never change results — but it must never DROP a row the engine
    filter keeps, hence the conservatism."""
    out = []
    for name, op, val in pushed or ():
        sql_op = {"==": "=", "<": "<", "<=": "<=",
                  ">": ">", ">=": ">="}.get(op)
        if sql_op is None:
            continue
        if isinstance(val, str):
            if sql_op != "=":
                continue
            lit = "'" + val.replace("'", "''") + "'"
        else:
            lit = str(int(val))
        out.append(f'"{name}" {sql_op} {lit}')
    return out


# ---------------------------------------------------------------------------
# read path (JDBCRDD.scanTable)
# ---------------------------------------------------------------------------

def _table_expr(options: Dict[str, str]) -> str:
    table = options.get("dbtable")
    query = options.get("query")
    if table and query:
        raise AnalysisException("specify either dbtable or query, not both")
    if query:
        return f"({query}) {_SUBQ}"
    if not table:
        raise AnalysisException("jdbc source requires a dbtable or query "
                                "option")
    return table


def _select_sql(options, columns, pushed, part_pred: Optional[str],
                limit: Optional[int] = None) -> str:
    cols = "*"
    if columns is not None:
        cols = ", ".join(f'"{c}"' for c in columns) if columns else "1"
    where = _pushed_sql(pushed)
    if part_pred:
        where.append(f"({part_pred})")
    sql = f"SELECT {cols} FROM {_table_expr(options)}"
    if where:
        sql += " WHERE " + " AND ".join(where)
    if limit is not None:
        sql += f" LIMIT {int(limit)}"
    return sql


def _rows_to_table(names: List[str], rows: List[tuple]):
    """Column-major pyarrow table from fetched DB rows, with type
    inference the DB cannot provide (DB-API description type codes are
    driver-specific): int→int64, float (or int/float mix)→float64,
    str→string, bytes→binary, bool→bool; all-NULL columns are typed
    ``pa.null()`` so partition concatenation promotes them to whatever
    the other partitions carry.  sqlite stores dates as TEXT — they
    arrive as strings, and ``to_date``/casts take it from there
    (documented divergence from the JVM's typed ResultSet getters)."""
    import pyarrow as pa
    cols = list(zip(*rows)) if rows else [() for _ in names]
    arrays = []
    for vals in cols:
        nn = [v for v in vals if v is not None]
        if not nn:
            t = pa.null()
        elif all(isinstance(v, bool) for v in nn):
            t = pa.bool_()
        elif all(isinstance(v, int) and not isinstance(v, bool)
                 for v in nn):
            t = pa.int64()
        elif all(isinstance(v, (int, float)) and not isinstance(v, bool)
                 for v in nn):
            t = pa.float64()
            vals = tuple(None if v is None else float(v) for v in vals)
        elif all(isinstance(v, bytes) for v in nn):
            t = pa.binary()
        else:
            t = pa.string()
            vals = tuple(None if v is None else str(v) for v in vals)
        arrays.append(pa.array(list(vals), t))
    return pa.table(dict(zip(names, arrays)))


#: arrow schema per (url, dbtable/query): ONE inference per relation so
#: every scan delivers the dtypes the planner resolved against, even when
#: a pushed WHERE or a partition predicate leaves a column all-NULL
_ARROW_SCHEMA_CACHE: Dict[tuple, object] = {}


def _arrow_schema(url: str, options: Dict[str, str], sample_rows: int = 200):
    """Relation arrow schema from a LIMIT-sample probe (cursor
    descriptions carry no portable types; ``JDBCRDD.resolveTable`` uses
    ResultSetMetaData — the DB-API equivalent is value inference).
    Cached: the schema is resolved once per relation and every scan CASTS
    to it, exactly like the reference fixing the schema at resolveTable
    time.  A column NULL throughout the sample degrades to string."""
    import pyarrow as pa
    key = (_normalize_url(url), options.get("dbtable"),
           options.get("query"))
    if key in _ARROW_SCHEMA_CACHE:
        return _ARROW_SCHEMA_CACHE[key]
    conn, _style = connect(url, options)
    try:
        cur = conn.cursor()
        sql = _select_sql(options, None, None, None, limit=sample_rows)
        try:
            cur.execute(sql)
        except Exception as e:
            raise AnalysisException(
                f"jdbc schema probe failed ({e}); query was: {sql}") from e
        names = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    t = _rows_to_table(names, rows)
    fields = [pa.field(f.name, pa.string() if pa.types.is_null(f.type)
              else f.type) for f in t.schema]
    schema = pa.schema(fields)
    _ARROW_SCHEMA_CACHE[key] = schema
    return schema


def read_table(urls: List[str], options: Dict[str, str], columns=None,
               pushed=None, target=None):
    """All partition queries of one jdbc relation → one pyarrow table
    (the eager analog of JDBCRDD's per-partition compute), cast to the
    relation's resolved schema so batch dtypes never drift from the plan.
    ``target`` (an arrow schema) is the RELATION's resolved schema —
    user-declared via ``.schema(...)`` or sample-inferred at load()."""
    import pyarrow as pa
    if target is None:
        target = _arrow_schema(urls[0], options)
    conn, _style = connect(urls[0], options)
    try:
        cur = conn.cursor()
        tables = []
        names: Optional[List[str]] = None
        for pred in partition_predicates(options):
            sql = _select_sql(options, columns, pushed, pred)
            try:
                cur.execute(sql)
            except Exception as e:
                raise AnalysisException(
                    f"jdbc scan failed ({e}); query was: {sql}") from e
            if names is None:
                names = [d[0] for d in cur.description]
            fetch = int(options.get("fetchsize", "10000") or 10000)
            rows: List[tuple] = []
            while True:
                chunk = cur.fetchmany(fetch)
                if not chunk:
                    break
                rows.extend(chunk)
            tables.append(_rows_to_table(names, rows))
        out = pa.concat_tables(tables, promote_options="permissive")
    finally:
        conn.close()
    cast = pa.schema([target.field(n) if target.get_field_index(n) >= 0
                      else out.schema.field(n) for n in out.column_names])
    try:
        return out.cast(cast)
    except Exception as e:
        raise AnalysisException(
            f"jdbc scan returned values outside the resolved schema "
            f"({e}); if the schema was sample-inferred and the sample is "
            "unrepresentative, declare it explicitly with "
            ".schema(...) — the declared schema becomes the scan's cast "
            "target") from e


def table_schema(url: str, options: Dict[str, str]):
    """Engine schema of a jdbc relation (see ``_arrow_schema``)."""
    import pyarrow as pa
    from .io import _table_to_batch
    schema = _arrow_schema(url, options)
    return _table_to_batch(schema.empty_table()).schema


#: COUNT(*) per (url, relation) — a planning STATISTIC, probed repeatedly
#: by multi-join planning; evicted by write_table, otherwise as stale as
#: any planner stat (the reference's ANALYZE-gathered stats likewise)
_COUNT_CACHE: Dict[tuple, int] = {}


def count_rows(url: str, options: Dict[str, str]) -> Optional[int]:
    """Planning row-count stat; None (never an exception) when the DB is
    unreachable so planning degrades to no-stats like the file formats."""
    key = (_normalize_url(url), options.get("dbtable"),
           options.get("query"))
    if key in _COUNT_CACHE:
        return _COUNT_CACHE[key]
    try:
        conn, _style = connect(url, options)
        try:
            cur = conn.cursor()
            cur.execute(f"SELECT COUNT(*) FROM {_table_expr(options)}")
            n = int(cur.fetchone()[0])
        finally:
            conn.close()
    except Exception:
        return None
    _COUNT_CACHE[key] = n
    return n


def _evict_relation(url: str, name: str) -> None:
    """Drop cached schema/count entries for one written table — the one
    invalidation token a mutable store does give us is OUR OWN write."""
    key = (_normalize_url(url), name, None)
    _ARROW_SCHEMA_CACHE.pop(key, None)
    _COUNT_CACHE.pop(key, None)


# ---------------------------------------------------------------------------
# write path (JdbcUtils.createTable / savePartition)
# ---------------------------------------------------------------------------

#: keyed by ``str(pa_type)`` — note pyarrow names floats "double"/"float"
_SQL_TYPES = {
    "int64": "BIGINT", "int32": "INTEGER", "int16": "SMALLINT",
    "int8": "SMALLINT", "double": "DOUBLE PRECISION", "float": "REAL",
    "bool": "BOOLEAN", "string": "TEXT", "large_string": "TEXT",
    "binary": "BLOB", "date32[day]": "DATE", "timestamp[us]": "TIMESTAMP",
}


def _placeholders(style: str, n: int) -> str:
    """VALUES placeholders for every PEP 249 paramstyle.  `named` and
    `pyformat` bind by name — ``write_table`` passes dict rows for those."""
    if style == "format":
        return ", ".join(["%s"] * n)
    if style == "pyformat":
        return ", ".join(f"%(p{i})s" for i in range(n))
    if style == "named":
        return ", ".join(f":p{i}" for i in range(n))
    if style == "numeric":
        return ", ".join(f":{i + 1}" for i in range(n))
    return ", ".join(["?"] * n)


def write_table(table, url: str, name: str, mode: str,
                options: Dict[str, str]) -> None:
    """Arrow table → database table.  DDL from the arrow schema; rows via
    batched parameterized INSERTs in ONE transaction (savePartition's
    commit discipline: all rows or none)."""
    _evict_relation(url, name)
    conn, style = connect(url, {**options, "dbtable": name}, create=True)
    try:
        cur = conn.cursor()
        exists = True
        try:
            cur.execute(f'SELECT 1 FROM "{name}" LIMIT 1')
            cur.fetchall()
        except Exception:
            exists = False
            conn.rollback()
        if exists:
            if mode == "errorifexists":
                raise AnalysisException(f"jdbc table {name} already exists")
            if mode == "ignore":
                return
            if mode == "overwrite":
                cur.execute(f'DROP TABLE "{name}"')
                exists = False
        if not exists:
            cols = ", ".join(
                f'"{f.name}" {_SQL_TYPES.get(str(f.type), "TEXT")}'
                for f in table.schema)
            cur.execute(f'CREATE TABLE "{name}" ({cols})')
        ph = _placeholders(style, table.num_columns)
        # explicit column list: append mode must bind by NAME against a
        # pre-existing table whose column order may differ (the silent
        # positional-scramble JdbcUtils.getInsertStatement also avoids)
        collist = ", ".join(f'"{c}"' for c in table.column_names)
        sql = f'INSERT INTO "{name}" ({collist}) VALUES ({ph})'
        pydict = table.to_pydict()
        rows = list(zip(*[pydict[c] for c in table.column_names])) \
            if table.num_rows else []
        if style in ("named", "pyformat"):
            rows = [{f"p{i}": v for i, v in enumerate(r)} for r in rows]
        batch = int(options.get("batchsize", "1000") or 1000)
        for i in range(0, len(rows), batch):
            cur.executemany(sql, rows[i:i + batch])
        conn.commit()
    finally:
        conn.close()
