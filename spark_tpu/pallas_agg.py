"""Pallas TPU kernel for grouped aggregation (the MXU hash-map).

The grouped-aggregate hot loop is a one-hot matmul: for each row tile,
``one_hot(bucket) @ planes`` scatters each row's 8-bit limb planes into its
bucket row.  Formulated in plain XLA the one-hot tile round-trips through
HBM (N x B bf16 — tens of GB at bench sizes) and fusion decisions are
fragile; this kernel builds each ``(L, BB)`` one-hot tile in VMEM from an
iota compare, feeds the MXU directly, and accumulates an int32 ``(B, P)``
result in VMEM scratch across the whole grid — HBM traffic is ONE pass
over the inputs.

Runtime bucket-chunk skipping: buckets are processed in ``BB``-wide
chunks, and a scalar-prefetch argument ``n_active`` (derived from the
actual key range, a traced value) lets the kernel skip chunks that cannot
contain a live bucket — the common "1k distinct keys in a 4k-bucket
table" case does 1/8th of the matmul work without recompiling.

Exactness: one-hot entries are {0,1} bf16, plane values are {0..255}
bf16 (both exact); each per-tile f32 dot accumulates at most
255*L < 2^24 so f32 is exact; the cross-tile int32 accumulator is exact
while 255*N < 2^31 (the wrapper chunks input batches above that).

Reference parity: this is the TPU replacement for the Tungsten vectorized
hash map (`sql/core/.../aggregate/VectorizedHashMapGenerator.scala`,
`AggregateBenchmark.scala:125-131` "codegen = T hashmap = T").
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

Array = Any

# Tile sizes: L rows per tile (sublane-dim of the one-hot, multiple of 8),
# BB buckets per chunk (lane-dim, multiple of 128).  VMEM at the defaults:
# one-hot (L, BB) bf16 = 1 MB, acc (B<=8192, P->128 lanes) i32 <= 4 MB.
_L = 1024
_BB = 512
_MAX_B = 8192          # full-accumulator variant cap (acc must fit VMEM)
_MAX_CHUNK_ROWS = 1 << 23    # 255 * 2^23 < 2^31: int32 accumulator exact


try:  # pallas imports fail cleanly on backends without Mosaic
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    HAVE_PALLAS = True
except Exception:  # pragma: no cover
    HAVE_PALLAS = False


def _kernel(nact_ref, bucket_ref, planes_ref, out_ref, acc_ref, *, T, BCH, L,
            BB, P):
    t = pl.program_id(0)
    bj = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        acc_ref[pl.ds(bj * BB, BB), :] = jnp.zeros((BB, P), jnp.int32)

    @pl.when(bj < nact_ref[0])
    def _active():
        b = bucket_ref[0, :]                                   # (L,) int32
        iota = jax.lax.broadcasted_iota(jnp.int32, (L, BB), 1) + bj * BB
        oh = (b[:, None] == iota).astype(jnp.bfloat16)         # (L, BB)
        pt = jax.lax.dot_general(
            oh, planes_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)                # (BB, P)
        acc_ref[pl.ds(bj * BB, BB), :] += pt.astype(jnp.int32)

    @pl.when((t == T - 1) & (bj == BCH - 1))
    def _fin():
        out_ref[:] = acc_ref[:]


@functools.partial(jax.jit, static_argnames=("B", "L", "BB", "interpret"))
def _accumulate_chunk(bucket32: Array, planes: Array, n_active: Array, *,
                      B: int, L: int, BB: int, interpret: bool) -> Array:
    n = bucket32.shape[0]
    P = planes.shape[1]
    n_pad = ((n + L - 1) // L) * L
    if n_pad != n:
        # zero planes contribute nothing regardless of bucket value
        bucket32 = jnp.concatenate(
            [bucket32, jnp.zeros(n_pad - n, jnp.int32)])
        planes = jnp.concatenate(
            [planes, jnp.zeros((n_pad - n, P), planes.dtype)])
    B_pad = ((B + BB - 1) // BB) * BB
    T = n_pad // L
    BCH = B_pad // BB

    # index maps must stay i32: under jax_enable_x64 a bare Python 0
    # lowers as an i64 constant, which Mosaic refuses to legalize
    # ("failed to legalize operation 'func.func'", first seen on real
    # v5e hardware 2026-07-31 — interpret mode never catches this)
    zero = np.int32(0)          # numpy scalar: untraced, keeps i32 dtype
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(T, BCH),
        in_specs=[
            pl.BlockSpec((1, L), lambda t, bj, n: (zero, t)),
            pl.BlockSpec((L, P), lambda t, bj, n: (t, zero)),
        ],
        out_specs=pl.BlockSpec((B_pad, P), lambda t, bj, n: (zero, zero)),
        scratch_shapes=[pltpu.VMEM((B_pad, P), jnp.int32)],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, T=T, BCH=BCH, L=L, BB=BB, P=P),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B_pad, P), jnp.int32),
        interpret=interpret,
    )(n_active.reshape(1).astype(jnp.int32),
      bucket32.reshape(1, n_pad),
      planes.astype(jnp.bfloat16))
    return out[:B]


def supported(B: int) -> bool:
    import os
    if os.environ.get("SPARK_TPU_DISABLE_PALLAS"):
        # kill switch: lets the bench orchestrator retry a run with the
        # plain-XLA einsum path if Mosaic lowering breaks on some backend
        return False
    return HAVE_PALLAS and B <= _MAX_B


def n_active_chunks(xp, prod, B: int):
    """Traced int32 chunk count covering buckets [0, prod) — the kernel
    skips chunks >= this.  Owned here so the chunk width stays private."""
    import numpy as np
    return xp.clip(xp.ceil(prod / np.float64(_BB)), 1.0,
                   float(-(-B // _BB))).astype(np.int32)


def grouped_accumulate(bucket32: Array, planes: Array, n_active: Array,
                       B: int, *, interpret: bool = False) -> Array:
    """Per-bucket column sums: out[b, p] = sum(planes[i, p] for bucket[i]==b).

    bucket32: (N,) int32 in [0, B); rows whose planes are all-zero may carry
    any bucket value.  planes: (N, P) with values in {0..255}.  n_active: a
    traced int32 scalar — number of leading ceil(B/BB) bucket chunks that can
    contain a live bucket (pass B//BB rounded up to skip nothing).
    Returns (B, P) int64, bit-exact.
    """
    n = bucket32.shape[0]
    if n <= _MAX_CHUNK_ROWS:
        return _accumulate_chunk(bucket32, planes, n_active, B=B, L=_L,
                                 BB=_BB, interpret=interpret).astype(jnp.int64)
    tot = jnp.zeros((B, planes.shape[1]), jnp.int64)
    for s in range(0, n, _MAX_CHUNK_ROWS):
        e = min(s + _MAX_CHUNK_ROWS, n)
        tot = tot + _accumulate_chunk(
            bucket32[s:e], planes[s:e], n_active, B=B, L=_L, BB=_BB,
            interpret=interpret).astype(jnp.int64)
    return tot
