"""Datasource IO: DataFrameReader / DataFrameWriter + file relation loading.

The analog of `sql/core/.../execution/datasources/` (`DataSource.scala`
resolution, `FileFormat.scala` implementations, `PartitioningUtils` partition
discovery, `FileFormatWriter.scala`) re-based on Arrow:

* parquet/csv/json decode through pyarrow's C++ readers straight into
  columnar host memory — the role `VectorizedParquetRecordReader.java` plays
  in the reference — then transfer to device as SoA arrays.
* partition discovery parses `key=value` directory components
  (`PartitioningUtils.parsePathFragment` analog) and materializes partition
  columns.
* writers emit Spark-compatible directory layouts: `part-*` files inside the
  target directory, `key=value` subdirectories under `partitionBy`, and a
  `_SUCCESS` marker.

Reads are eager at plan time (a FileRelation resolves to one host batch,
cached by path+mtime); the scan operator streams it to device.  Multi-batch
streaming scans arrive with the multi-stage runner.
"""

from __future__ import annotations

import glob as _glob
import json as _json
import os
from typing import Any, Dict, List, Optional

import numpy as np

from . import types as T
from .columnar import ColumnBatch, ColumnVector, PrebuiltColumn as \
    _PrebuiltColumn
from .expressions import AnalysisException
from .sql import logical as L

__all__ = ["DataFrameReader", "DataFrameWriter", "read_file_relation"]

_DATA_EXTS = {".parquet", ".csv", ".json", ".txt", ".text"}


# ---------------------------------------------------------------------------
# schema mapping (arrow <-> engine types)
# ---------------------------------------------------------------------------

def _arrow_to_engine(at) -> T.DataType:
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return T.boolean
    if pa.types.is_int8(at):
        return T.int8
    if pa.types.is_int16(at):
        return T.int16
    if pa.types.is_int32(at):
        return T.int32
    if pa.types.is_int64(at) or pa.types.is_unsigned_integer(at):
        return T.int64
    if pa.types.is_float32(at):
        return T.float32
    if pa.types.is_floating(at):
        return T.float64
    if pa.types.is_decimal(at):
        return T.DecimalType(at.precision, at.scale)
    if pa.types.is_date(at):
        return T.date
    if pa.types.is_timestamp(at):
        return T.timestamp
    if pa.types.is_string(at) or pa.types.is_large_string(at) \
            or pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return T.string
    if pa.types.is_null(at):
        return T.string
    raise AnalysisException(f"unsupported arrow type for TPU engine: {at}")


def _engine_to_arrow(dt: T.DataType):
    import pyarrow as pa
    if isinstance(dt, T.BooleanType):
        return pa.bool_()
    if isinstance(dt, T.ByteType):
        return pa.int8()
    if isinstance(dt, T.ShortType):
        return pa.int16()
    if isinstance(dt, T.IntegerType):
        return pa.int32()
    if isinstance(dt, T.LongType):
        return pa.int64()
    if isinstance(dt, T.FloatType):
        return pa.float32()
    if isinstance(dt, T.DoubleType):
        return pa.float64()
    if isinstance(dt, T.DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, T.DateType):
        return pa.date32()
    if isinstance(dt, T.TimestampType):
        return pa.timestamp("us")
    if isinstance(dt, T.StringType):
        return pa.string()
    raise AnalysisException(f"cannot write type {dt}")


def _table_to_batch(table, extra_cols: Optional[Dict[str, Any]] = None
                    ) -> ColumnBatch:
    """Arrow table → host ColumnBatch (+appended partition columns).

    Numeric/temporal columns convert VECTORIZED (arrow fill_null + numpy
    view), including nullable ones — the per-value pylist lane is only
    for strings (dictionary encoding needs the words) and decimals.
    This is the `VectorizedParquetRecordReader.java` half of the scan
    hot path; the pylist fallback was 10× the whole scan cost at 2M+
    rows."""
    import pyarrow as pa
    data: Dict[str, Any] = {}
    fields: List[T.StructField] = []
    n = table.num_rows
    for col_name, col in zip(table.column_names, table.columns):
        at = col.type
        dt = _arrow_to_engine(at)
        arr = col.combine_chunks()
        if dt.is_string:
            data[col_name] = arr.to_pylist()
        elif isinstance(dt, T.DecimalType):
            scaled = [None if v is None else int(v.scaled_value)
                      for v in arr.to_pylist()]
            data[col_name] = np.array(
                [0 if v is None else v for v in scaled], np.int64)
            # nulls handled below via pylist path when present
            if arr.null_count:
                data[col_name] = scaled
        else:
            if isinstance(dt, T.DateType):
                arr = arr.cast(pa.date32()).cast(pa.int32())
                np_dtype = np.int32
            elif isinstance(dt, T.TimestampType):
                arr = arr.cast(pa.timestamp("us")).cast(pa.int64())
                np_dtype = np.int64
            else:
                np_dtype = np.dtype(dt.np_dtype)
            valid = None
            if arr.null_count:
                valid = ~np.asarray(arr.is_null())
                fill = pa.scalar(False) if np_dtype == np.bool_ \
                    else pa.scalar(0, arr.type)
                arr = arr.fill_null(fill)
            vals = arr.to_numpy(zero_copy_only=False).astype(np_dtype,
                                                             copy=False)
            data[col_name] = _PrebuiltColumn(vals, dt, valid)
        fields.append(T.StructField(col_name, dt, True))
    if extra_cols:
        for k, v in extra_cols.items():
            data[k] = v
            if isinstance(v, np.ndarray):
                dt = T.np_dtype_to_engine(v.dtype)
            else:
                dt = T.string
            fields.append(T.StructField(k, dt, True))
    schema = T.StructType(fields)
    if n == 0 and not extra_cols:
        return ColumnBatch.empty(schema)
    return ColumnBatch.from_arrays(data, schema=schema)


# ---------------------------------------------------------------------------
# path resolution + partition discovery
# ---------------------------------------------------------------------------

def _resolve_paths(path_or_paths) -> List[str]:
    paths = ([path_or_paths] if isinstance(path_or_paths, str)
             else list(path_or_paths))
    out: List[str] = []
    for p in paths:
        if any(ch in p for ch in "*?["):
            out += sorted(_glob.glob(p))
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if not d.startswith(("_", ".")))
                for f in sorted(files):
                    if f.startswith(("_", ".")):
                        continue
                    out.append(os.path.join(root, f))
        elif os.path.exists(p):
            out.append(p)
        else:
            raise AnalysisException(f"Path does not exist: {p}")
    if not out:
        raise AnalysisException(f"no input files found in {path_or_paths}")
    return out


def _partition_values(file_path: str, base: str) -> Dict[str, str]:
    """Parse `key=value` directory components below `base`."""
    rel = os.path.relpath(os.path.dirname(file_path), base)
    vals: Dict[str, str] = {}
    if rel == ".":
        return vals
    for comp in rel.split(os.sep):
        if "=" in comp:
            k, v = comp.split("=", 1)
            vals[k] = v
    return vals


def _infer_partition_column(raw: List[str]):
    """Spark infers partition value types (int, double, string)."""
    try:
        return np.array([int(v) for v in raw], np.int64)
    except ValueError:
        pass
    try:
        return np.array([float(v) for v in raw], np.float64)
    except ValueError:
        return raw


# ---------------------------------------------------------------------------
# format readers (host side, arrow-backed)
# ---------------------------------------------------------------------------

#: observable scan counters (ParquetReadBenchmark-style evidence that
#: pruning/pushdown actually narrowed the read); reset freely in tests
SCAN_STATS = {"files": 0, "row_groups": 0, "row_groups_skipped": 0,
              "rows": 0, "columns_read": 0}


def _rg_keep(pf, pushed: Optional[List[tuple]]) -> Optional[List[int]]:
    """Row groups that MAY contain matching rows, by footer min/max stats.

    ``pushed`` holds advisory ``(col, op, value)`` conjuncts; a row group
    is skipped only when its stats PROVE no row satisfies a conjunct
    (``ParquetFilters.scala`` + ``VectorizedParquetRecordReader`` role).
    Returns None when nothing can be skipped (avoids the per-group read
    path)."""
    if not pushed:
        return None
    md = pf.metadata
    name_to_idx = {md.schema.column(i).path: i
                   for i in range(md.num_columns)}
    keep: List[int] = []
    skipped = 0
    for rg in range(md.num_row_groups):
        alive = True
        for col, op, val in pushed:
            ci = name_to_idx.get(col)
            if ci is None:
                continue
            st = md.row_group(rg).column(ci).statistics
            if st is None or not st.has_min_max:
                continue
            try:
                lo, hi = st.min, st.max
                if isinstance(val, str) and isinstance(lo, bytes):
                    lo, hi = lo.decode("utf-8", "replace"), \
                        hi.decode("utf-8", "replace")
                if type(lo) is not type(val) and not (
                        isinstance(lo, (int, float))
                        and isinstance(val, (int, float))):
                    continue
                if (op == "==" and (val < lo or val > hi)) \
                        or (op == "<" and lo >= val) \
                        or (op == "<=" and lo > val) \
                        or (op == ">" and hi <= val) \
                        or (op == ">=" and hi < val):
                    alive = False
                    break
            except Exception:
                continue
        if alive:
            keep.append(rg)
        else:
            skipped += 1
    SCAN_STATS["row_groups_skipped"] += skipped
    return keep if skipped else None


def _open_pruned(path: str, columns, pushed):
    """Open one parquet file for a pruned/pushed read: returns
    ``(pf, present, keep)`` and updates SCAN_STATS — the single definition
    behind both the eager and streaming scan paths."""
    import pyarrow.parquet as pq
    pf = pq.ParquetFile(path)
    present = None
    if columns is not None:
        names = set(pf.schema_arrow.names)
        present = [c for c in columns if c in names]
    SCAN_STATS["files"] += 1
    SCAN_STATS["columns_read"] += len(present) if present is not None \
        else pf.metadata.num_columns
    keep = _rg_keep(pf, pushed)
    SCAN_STATS["row_groups"] += pf.metadata.num_row_groups
    return pf, present, keep


def _read_parquet(paths: List[str], options, columns=None,
                  pushed=None) -> "Any":
    import pyarrow.parquet as pq
    import pyarrow as pa
    tables = []
    for p in paths:
        pf, present, keep = _open_pruned(p, columns, pushed)
        if keep is None:
            t = pq.read_table(p, columns=present)
        elif keep:
            t = pf.read_row_groups(keep, columns=present)
        else:
            t = pf.schema_arrow.empty_table()
            if present is not None:
                t = t.select(present)
        SCAN_STATS["rows"] += t.num_rows
        tables.append(t)
    return pa.concat_tables(tables, promote_options="permissive")


def _read_csv(paths: List[str], options) -> "Any":
    import pyarrow as pa
    import pyarrow.csv as pacsv
    header = str(options.get("header", "false")).lower() == "true"
    sep = options.get("sep", options.get("delimiter", ","))
    infer = str(options.get("inferschema", "false")).lower() == "true"
    null_value = options.get("nullvalue", "")
    tables = []
    for p in paths:
        read_opts = pacsv.ReadOptions(autogenerate_column_names=not header)
        parse_opts = pacsv.ParseOptions(delimiter=sep)
        conv = pacsv.ConvertOptions(null_values=[null_value, "null"])
        t = pacsv.read_csv(p, read_options=read_opts,
                           parse_options=parse_opts, convert_options=conv)
        if not header:
            t = t.rename_columns([f"_c{i}" for i in range(t.num_columns)])
        if not infer:
            t = t.cast(pa.schema([pa.field(f.name, pa.string())
                                  for f in t.schema]))
        tables.append(t)
    return pa.concat_tables(tables, promote_options="permissive")


def _read_json(paths: List[str], options) -> "Any":
    import pyarrow as pa
    import pyarrow.json as pajson
    tables = [pajson.read_json(p) for p in paths]
    return pa.concat_tables(tables, promote_options="permissive")


def _read_text(paths: List[str], options) -> "Any":
    import pyarrow as pa
    lines: List[str] = []
    for p in paths:
        with open(p, "r", encoding="utf-8") as f:
            lines += [ln.rstrip("\n") for ln in f]
    return pa.table({"value": pa.array(lines, pa.string())})


def _read_orc(paths: List[str], options, columns=None) -> "Any":
    """ORC via pyarrow.orc (`sql/hive/.../orc/OrcFileFormat.scala` role):
    column pruning pushes into the stripe reader; stats-based stripe
    skipping stays parquet-only (documented)."""
    import pyarrow as pa
    import pyarrow.orc as paorc
    tables = []
    strip = False
    for p in paths:
        f = paorc.ORCFile(p)
        cols = None if columns is None else \
            [c for c in columns if c in f.schema.names]
        if cols == []:
            # partition-dir-only projection: both ORCFile.read(columns=[])
            # and concat_tables of 0-column tables DROP the row count, so
            # carry one narrow column through the concat and strip after
            cols = [f.schema.names[0]]
            strip = True
        tables.append(f.read(columns=cols))
    out = pa.concat_tables(tables, promote_options="permissive")
    return out.select([]) if strip else out


_READERS = {
    "parquet": _read_parquet,
    "csv": _read_csv,
    "json": _read_json,
    "text": _read_text,
    "orc": _read_orc,
}


def _parquet_schema(raw_paths: List[str]) -> T.StructType:
    """Engine schema from parquet FOOTERS + partition directories — no data
    pages are read (the lazy half of ``DataSource.resolveRelation``)."""
    import pyarrow.parquet as pq
    files = _resolve_paths(raw_paths)
    base = raw_paths[0] if isinstance(raw_paths, list) else raw_paths
    base = base if os.path.isdir(base) else os.path.dirname(base)
    fields: List[T.StructField] = []
    seen: set = set()
    for f in files:
        for af in pq.ParquetFile(f).schema_arrow:
            if af.name not in seen:
                seen.add(af.name)
                fields.append(T.StructField(af.name,
                                            _arrow_to_engine(af.type), True))
    _append_partition_fields(files, base, seen, fields)
    return T.StructType(fields)


def _append_partition_fields(files, base, seen: set,
                             fields: List["T.StructField"]) -> None:
    """Partition-directory (k=v) columns, shared by every metadata-only
    schema reader (parquet footers, ORC metadata)."""
    part_vals: Dict[str, List[str]] = {}
    for f in files:
        for k, v in _partition_values(f, base).items():
            part_vals.setdefault(k, []).append(v)
    for k, vals in part_vals.items():
        if k in seen:
            continue
        inferred = _infer_partition_column(vals)
        dt = T.np_dtype_to_engine(inferred.dtype) \
            if isinstance(inferred, np.ndarray) else T.string
        fields.append(T.StructField(k, dt, True))


def _orc_schema(raw_paths: List[str]) -> T.StructType:
    """Engine schema from ORC file metadata — no stripes read."""
    import pyarrow.orc as paorc
    files = _resolve_paths(raw_paths)
    base = raw_paths[0] if isinstance(raw_paths, list) else raw_paths
    base = base if os.path.isdir(base) else os.path.dirname(base)
    fields: List[T.StructField] = []
    seen: set = set()
    for f in files:
        for af in paorc.ORCFile(f).schema:
            if af.name not in seen:
                seen.add(af.name)
                fields.append(T.StructField(af.name,
                                            _arrow_to_engine(af.type), True))
    _append_partition_fields(files, base, seen, fields)
    return T.StructType(fields)


_relation_cache: Dict[Any, ColumnBatch] = {}


def _load_batch(fmt: str, raw_paths: List[str], options: Dict[str, str],
                columns: Optional[List[str]] = None,
                pushed: Optional[List[tuple]] = None,
                engine_schema: Optional[T.StructType] = None) -> ColumnBatch:
    if fmt == "jdbc":
        # database relations: no filesystem paths, and NEVER cached (a
        # mutable store has no mtime-like invalidation token).  The
        # relation's resolved engine schema (user-declared or
        # sample-inferred) is the scan's cast target.
        import pyarrow as pa
        from . import jdbc as _jdbc
        urls = [raw_paths] if isinstance(raw_paths, str) else list(raw_paths)
        target = None
        if engine_schema is not None:
            target = pa.schema([pa.field(f.name,
                                         _engine_to_arrow(f.dataType))
                                for f in engine_schema.fields])
        return _table_to_batch(_jdbc.read_table(urls, options,
                                                columns=columns,
                                                pushed=pushed,
                                                target=target))
    files = _resolve_paths(raw_paths)
    key = (fmt, tuple(files), tuple(sorted(options.items())),
           tuple(os.path.getmtime(f) for f in files),
           None if columns is None else tuple(columns),
           None if pushed is None else tuple(pushed))
    if key in _relation_cache:
        return _relation_cache[key]
    base_reader = _READERS.get(fmt)
    if base_reader is None:
        raise AnalysisException(f"unsupported format: {fmt}")
    if fmt == "parquet":
        def reader(paths, opts):
            return _read_parquet(paths, opts, columns=columns, pushed=pushed)
    elif fmt == "orc" and columns is not None:
        def reader(paths, opts):
            return _read_orc(paths, opts, columns=columns)
    elif columns is not None:
        def reader(paths, opts):
            t = base_reader(paths, opts)
            sel = [c for c in columns if c in t.column_names]
            return t.select(sel)
    else:
        reader = base_reader
    # group files by partition values (from the first existing base dir)
    base = raw_paths[0] if isinstance(raw_paths, list) else raw_paths
    base = base if os.path.isdir(base) else os.path.dirname(base)
    part_of = {f: _partition_values(f, base) for f in files}
    part_keys: List[str] = []
    for f in files:
        for k in part_of[f]:
            if k not in part_keys and (columns is None or k in columns):
                part_keys.append(k)
    table = reader(files, options)
    extra = None
    if part_keys:
        # re-read per file to align partition values with row counts
        import pyarrow as pa
        per_file = [reader([f], options) for f in files]
        cols: Dict[str, List[str]] = {k: [] for k in part_keys}
        for f, t in zip(files, per_file):
            for k in part_keys:
                cols[k] += [part_of[f].get(k, "")] * t.num_rows
        table = pa.concat_tables(per_file, promote_options="permissive")
        extra = {k: _infer_partition_column(v) for k, v in cols.items()}
    batch = _table_to_batch(table, extra)
    _relation_cache[key] = batch
    if len(_relation_cache) > 64:
        _relation_cache.pop(next(iter(_relation_cache)))
    return batch


def read_file_relation(rel: L.FileRelation, session) -> ColumnBatch:
    return _load_batch(rel.fmt, rel.paths, rel.options,
                       columns=getattr(rel, "columns", None),
                       pushed=getattr(rel, "pushed_filters", None),
                       engine_schema=getattr(rel, "_schema", None))


# ---------------------------------------------------------------------------
# streamed (multi-batch) scans — FileScanRDD.scala analog
# ---------------------------------------------------------------------------

_ROW_COUNT_CACHE: dict = {}


def file_row_count(rel: L.FileRelation) -> Optional[int]:
    """Total rows WITHOUT loading data when possible (parquet metadata);
    other formats load (host-cached) and count.  Memoized per resolved
    file list + mtimes — multi-join planning probes the same dimension
    files repeatedly."""
    import os
    if rel.fmt == "jdbc":
        from . import jdbc as _jdbc
        return _jdbc.count_rows(rel.paths[0], rel.options)  # never cached
    try:
        files = _resolve_paths(rel.paths)
    except AnalysisException:
        return None
    key = tuple((f, os.path.getmtime(f)) for f in files)
    if key in _ROW_COUNT_CACHE:
        return _ROW_COUNT_CACHE[key]
    if rel.fmt == "parquet":
        import pyarrow.parquet as pq
        n = sum(pq.ParquetFile(f).metadata.num_rows for f in files)
    else:
        st = analyzed_stats(rel)
        if st and st.get("rows") is not None:
            n = int(st["rows"])     # ANALYZE result: no data load needed
        else:
            batch = _load_batch(rel.fmt, rel.paths, rel.options)
            n = int(np.asarray(batch.num_rows()))
    _ROW_COUNT_CACHE[key] = n
    return n


#: ANALYZE TABLE results, keyed by the relation's identity at ANALYZE
#: time (files+mtimes, or the jdbc url+table).  The CBO fallback for
#: formats without free footer statistics (csv/json/text/orc/jdbc) —
#: parquet keeps its exact, always-fresh footer path.  Mirrors the
#: reference's ANALYZE-gathered `statsEstimation/` stats, including
#: their staleness model (here: invalidated when file mtimes change).
_ANALYZED_STATS: Dict[Any, dict] = {}


def _rel_stats_key(rel: L.FileRelation):
    """Identity of a relation FOR STATS PURPOSES: format + read options
    (header/schema options change the logical table over the same bytes)
    + files with mtimes (staleness token); jdbc: url + table/query."""
    opts = tuple(sorted((str(k), str(v))
                        for k, v in (rel.options or {}).items()))
    if rel.fmt == "jdbc":
        return ("jdbc", rel.paths[0], rel.options.get("dbtable"),
                rel.options.get("query"))
    try:
        files = _resolve_paths(rel.paths)
    except AnalysisException:
        return None
    return (rel.fmt, opts) + tuple(
        (f, os.path.getmtime(f)) for f in files)


def stats_key_token(rel: L.FileRelation):
    """JSON-round-tripped form of the stats key, captured at ANALYZE
    time and persisted with the stats: a catalog load re-registers them
    ONLY when the current key still matches — the staleness gate."""
    import json as _json
    k = _rel_stats_key(rel)
    return None if k is None else _json.loads(_json.dumps(k))


def register_analyzed_stats(rel: L.FileRelation, stats: dict) -> None:
    """Install ANALYZE TABLE results for this relation's current files."""
    key = _rel_stats_key(rel)
    if key is not None:
        _ANALYZED_STATS[key] = stats


def analyzed_stats(rel: L.FileRelation) -> Optional[dict]:
    key = _rel_stats_key(rel)
    return None if key is None else _ANALYZED_STATS.get(key)


_COLUMN_STATS_CACHE: dict = {}


def file_column_stats(rel: L.FileRelation) -> Dict[str, dict]:
    """Per-column {min, max, null_count, total} from parquet FOOTERS — the
    free column statistics the reference's CBO keeps in
    `catalyst/.../plans/logical/statsEstimation/` (there gathered by
    ANALYZE TABLE; here always available because parquet already wrote
    them).  Non-parquet formats fall back to ANALYZE TABLE results
    (``analyzed_stats``); memoized per file list + mtimes."""
    if rel.fmt != "parquet":
        st = analyzed_stats(rel)
        return st.get("columns", {}) if st else {}
    try:
        files = _resolve_paths(rel.paths)
    except AnalysisException:
        return {}
    key = tuple((f, os.path.getmtime(f)) for f in files)
    if key in _COLUMN_STATS_CACHE:
        return _COLUMN_STATS_CACHE[key]
    import pyarrow.parquet as pq
    out: Dict[str, dict] = {}
    for f in files:
        md = pq.ParquetFile(f).metadata
        names = {md.schema.column(i).path: i
                 for i in range(md.num_columns)}
        for name, ci in names.items():
            rec = out.setdefault(name, {"min": None, "max": None,
                                        "null_count": 0, "total": 0})
            rec["total"] += md.num_rows
            for rg in range(md.num_row_groups):
                st = md.row_group(rg).column(ci).statistics
                if st is None:
                    continue
                if st.null_count is not None:
                    rec["null_count"] += st.null_count
                if not st.has_min_max:
                    continue
                lo, hi = st.min, st.max
                if isinstance(lo, bytes):
                    lo = lo.decode("utf-8", "replace")
                    hi = hi.decode("utf-8", "replace")
                try:
                    if rec["min"] is None or lo < rec["min"]:
                        rec["min"] = lo
                    if rec["max"] is None or hi > rec["max"]:
                        rec["max"] = hi
                except TypeError:
                    pass
    _COLUMN_STATS_CACHE[key] = out
    return out


_NDV_CACHE: Dict[Any, Dict[str, float]] = {}


def file_column_ndv(rel: L.FileRelation, columns) -> Dict[str, float]:
    """Estimated distinct-value counts for ``columns`` (the NDV half of
    the reference's CBO statistics, `statsEstimation/` — gathered there
    by ANALYZE TABLE, here by a one-row-group sample at plan time).

    Estimator: distinct count over the first row group of the first
    file; if the sample's distinct ratio is saturated (<90% unique) the
    domain is assumed reached (dimension keys, enums), otherwise the
    count scales linearly with the table (near-unique keys).  Memoized
    per (files, mtimes, columns).  Non-parquet formats use ANALYZE TABLE
    results when present."""
    if rel.fmt != "parquet":
        st = analyzed_stats(rel)
        if not st:
            return {}
        return {c: rec["ndv"] for c, rec in st.get("columns", {}).items()
                if c in columns and rec.get("ndv") is not None}
    try:
        files = _resolve_paths(rel.paths)
    except AnalysisException:
        return {}
    # ONE cache entry per file set, extended per newly-requested column —
    # reorder_joins probes one key column at a time, and per-(files,
    # column) keys would re-open footers for every probe
    key = tuple((f, os.path.getmtime(f)) for f in files)
    cached = _NDV_CACHE.setdefault(key, {})
    missing = [c for c in columns if c not in cached]
    if not missing:
        return cached
    import pyarrow.parquet as pq
    try:
        pf = pq.ParquetFile(files[0])
        present = [c for c in missing if c in pf.schema_arrow.names]
        if present:
            sample = pf.read_row_group(0, columns=present)
            total = file_row_count(rel) or sample.num_rows  # memoized sum
            n = max(sample.num_rows, 1)
            for c in present:
                uniq = len(sample.column(c).unique())
                if uniq < 0.9 * n:
                    cached[c] = float(uniq)            # saturated domain
                else:
                    cached[c] = float(uniq) * total / n  # near-unique key
    except Exception:
        pass
    return cached


def scan_file_batches(rel: L.FileRelation, batch_rows: int):
    """Yield host ColumnBatches of ≤ batch_rows rows each.

    Parquet streams record batches straight off the file (the
    VectorizedParquetRecordReader path — bounded host memory); other
    formats slice the host-cached table.  Partition-directory columns are
    appended per file."""
    columns = getattr(rel, "columns", None)
    pushed = getattr(rel, "pushed_filters", None)
    if rel.fmt == "jdbc":
        # database relation: one partitioned read (WHERE pushdown + column
        # pruning applied in SQL), sliced host-side like csv/json
        whole = _load_batch(rel.fmt, rel.paths, rel.options,
                            columns=columns, pushed=pushed,
                            engine_schema=getattr(rel, "_schema", None))
        n = int(np.asarray(whole.num_rows()))
        for start in range(0, max(n, 1), batch_rows):
            yield _slice_rows(whole, start, min(start + batch_rows, n))
        return
    files = _resolve_paths(rel.paths)
    base = rel.paths[0] if isinstance(rel.paths, list) else rel.paths
    base = base if os.path.isdir(base) else os.path.dirname(base)
    if rel.fmt == "parquet":
        import pyarrow as pa
        import pyarrow.parquet as pq
        yielded = False
        for f in files:
            pvals = _partition_values(f, base)
            if columns is not None:
                pvals = {k: v for k, v in pvals.items() if k in columns}
            pf, present, keep = _open_pruned(f, columns, pushed)
            kw = {} if keep is None else {"row_groups": keep}
            if keep == []:
                continue
            for rb in pf.iter_batches(batch_size=batch_rows,
                                      columns=present, **kw):
                table = pa.Table.from_batches([rb])
                SCAN_STATS["rows"] += table.num_rows
                extra = {k: _infer_partition_column([v] * table.num_rows)
                         for k, v in pvals.items()} or None
                yielded = True
                yield _table_to_batch(table, extra)
        if not yielded:
            # every row group was skipped: emit one empty batch so stage
            # runners still see the (pruned) schema
            yield ColumnBatch.empty(rel.schema())
        return
    whole = _load_batch(rel.fmt, rel.paths, rel.options, columns=columns)
    n = int(np.asarray(whole.num_rows()))
    # the cached batch is compacted on load (row_valid all-true prefix)
    for start in range(0, max(n, 1), batch_rows):
        stop = min(start + batch_rows, n)
        yield _slice_rows(whole, start, stop)


def scan_prefetch_depth(conf) -> int:
    """Resolve ``spark.tpu.scan.prefetchBatches``: -1 (auto) prefetches
    only when the per-batch step runs on an accelerator — on host-CPU
    XLA the decode thread competes with the step for the same cores."""
    from . import config as C
    d = conf.get(C.SCAN_PREFETCH_BATCHES)
    if d >= 0:
        return d
    try:
        import jax
        accel = jax.devices()[0].platform != "cpu"
    except Exception:
        accel = False
    return 2 if accel else 0


def prefetch_iter(inner, prep=None, depth: int = 2):
    """Iterate ``inner`` through a bounded background pipeline thread.

    The worker pulls items from ``inner`` and applies ``prep`` (string
    re-encode / pad / device transfer) up to ``depth`` items ahead of the
    consumer, so the host-side Arrow read + H2D copy of batch N+1 overlap
    the device step of batch N — the double-buffered scan pipeline of the
    reference's vectorized reader
    (`parquet/VectorizedParquetRecordReader.java:147`, which decodes the
    next page while the consuming operator drains the current batch;
    SURVEY §7 hard-part 4).  ``depth <= 0`` degrades to synchronous
    iteration.  Worker exceptions re-raise at the consuming site; early
    termination (break / generator close) stops the worker and closes
    ``inner`` so parquet file handles are released promptly."""
    if depth <= 0:
        for item in inner:
            yield prep(item) if prep is not None else item
        return
    import queue as _qmod
    import threading

    q: "_qmod.Queue" = _qmod.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(msg) -> None:
        # bounded put that aborts when the consumer has gone away
        while not stop.is_set():
            try:
                q.put(msg, timeout=0.2)
                return
            except _qmod.Full:
                continue

    def worker() -> None:
        try:
            try:
                for item in inner:
                    out = prep(item) if prep is not None else item
                    _put(("item", out))
                    if stop.is_set():
                        return
            finally:
                close = getattr(inner, "close", None)
                if close is not None:
                    close()
        except BaseException as e:  # noqa: BLE001 — re-raised by consumer
            _put(("raise", e))
        else:
            _put(("end", None))

    th = threading.Thread(target=worker, daemon=True, name="scan-prefetch")
    th.start()
    try:
        while True:
            kind, payload = q.get()
            if kind == "item":
                yield payload
            elif kind == "raise":
                raise payload
            else:
                return
    finally:
        stop.set()
        try:                       # unblock a worker stuck on a full queue
            while True:
                q.get_nowait()
        except _qmod.Empty:
            pass
        th.join(timeout=5)


def scan_string_dictionaries(rel: L.FileRelation,
                             batch_rows: int) -> Dict[str, tuple]:
    """One cheap pre-pass over a file relation collecting the GLOBAL sorted
    dictionary of every string column.

    Streamed scans encode every batch onto these fixed dictionaries so the
    per-batch jitted step never retraces on dictionary changes, and sort
    order on codes stays globally consistent (sorted-dictionary invariant
    of ``encode_strings``).  For parquet only the string columns are read."""
    schema = rel.schema()
    str_cols = [f.name for f in schema.fields if f.dataType.is_string]
    if not str_cols:
        return {}
    uniques: Dict[str, set] = {c: set() for c in str_cols}
    files = [] if rel.fmt == "jdbc" else _resolve_paths(rel.paths)
    if rel.fmt == "parquet":
        import pyarrow.compute as pc
        import pyarrow.parquet as pq
        for f in files:
            pf = pq.ParquetFile(f)
            present = [c for c in str_cols if c in pf.schema_arrow.names]
            if not present:
                continue
            for rb in pf.iter_batches(batch_size=batch_rows, columns=present):
                for c in present:
                    col = rb.column(rb.schema.get_field_index(c))
                    # dedup in native code; only the per-batch uniques
                    # become Python objects
                    uniques[c].update(
                        v for v in pc.unique(col).to_pylist()
                        if v is not None)
    else:
        # jdbc: prune the (uncached) SELECT to the string columns only
        cols = str_cols if rel.fmt == "jdbc" else None
        whole = _load_batch(rel.fmt, rel.paths, rel.options, columns=cols,
                            engine_schema=getattr(rel, "_schema", None)
                            if rel.fmt == "jdbc" else None)
        for c in str_cols:
            if c in whole.names:
                vec = whole.column(c)
                if vec.dictionary:
                    uniques[c].update(vec.dictionary)
    # partition-directory columns (string-typed) also need fixed dicts
    base = rel.paths[0] if isinstance(rel.paths, list) else rel.paths
    base = base if os.path.isdir(base) else os.path.dirname(base)
    for f in files:
        for k, v in _partition_values(f, base).items():
            if k in uniques:
                uniques[k].add(v)
    return {c: tuple(sorted(s)) for c, s in uniques.items()}


def reencode_strings(batch: ColumnBatch,
                     fixed_dicts: Dict[str, tuple]) -> ColumnBatch:
    """Remap per-batch string codes onto fixed global dictionaries.

    Both dictionaries are sorted, so the remap table is one searchsorted."""
    if not fixed_dicts:
        return batch
    vectors = []
    for name, v in zip(batch.names, batch.vectors):
        target = fixed_dicts.get(name)
        if target is None or v.dictionary is None or \
                tuple(v.dictionary) == tuple(target):
            vectors.append(v)
            continue
        tarr = np.asarray(target, dtype=object)
        local = np.asarray(v.dictionary, dtype=object)
        remap = np.searchsorted(tarr, local).astype(np.int32) \
            if len(local) else np.zeros(0, np.int32)
        codes = np.asarray(v.data).astype(np.int64)
        new_codes = remap[np.clip(codes, 0, max(len(local) - 1, 0))] \
            if len(local) else np.zeros_like(codes, np.int32)
        new_codes = np.where(codes < 0, -1, new_codes).astype(np.int32)
        vectors.append(ColumnVector(new_codes, v.dtype, v.valid, tuple(target)))
    return ColumnBatch(list(batch.names), vectors, batch.row_valid,
                       batch.capacity)


def _slice_rows(batch: ColumnBatch, start: int, stop: int) -> ColumnBatch:
    from .columnar import ColumnVector as CV
    vectors = []
    for v in batch.vectors:
        data = np.asarray(v.data)[start:stop]
        valid = None if v.valid is None else np.asarray(v.valid)[start:stop]
        vectors.append(CV(data, v.dtype, valid, v.dictionary))
    rv = None if batch.row_valid is None \
        else np.asarray(batch.row_valid)[start:stop]
    out = ColumnBatch(batch.names, vectors, rv, stop - start)
    from .columnar import pad_capacity, pad_to_capacity
    return pad_to_capacity(out, pad_capacity(stop - start))


# ---------------------------------------------------------------------------
# DataFrameReader (`sql/DataFrameReader.scala` analog)
# ---------------------------------------------------------------------------

class DataFrameReader:
    def __init__(self, session):
        self._session = session
        self._fmt = "parquet"
        self._options: Dict[str, str] = {}
        self._schema: Optional[T.StructType] = None

    def format(self, source: str) -> "DataFrameReader":
        self._fmt = source.lower()
        return self

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[str(key).lower()] = str(value)
        return self

    def options(self, **opts) -> "DataFrameReader":
        for k, v in opts.items():
            self.option(k, v)
        return self

    def schema(self, s) -> "DataFrameReader":
        if isinstance(s, str):
            fields = []
            for part in s.split(","):
                name, tname = part.strip().rsplit(" ", 1)
                fields.append(T.StructField(name.strip(),
                                            T.type_for_name(tname)))
            s = T.StructType(fields)
        self._schema = s
        return self

    def load(self, path=None) -> "Any":
        from .sql.dataframe import DataFrame
        if path is None:
            raise AnalysisException("load() requires a path")
        paths = [path] if isinstance(path, str) else list(path)
        if self._schema is not None:
            schema = self._schema
        elif self._fmt == "parquet":
            # schema from footers only — a wide table must not be READ to
            # be *referenced*; pruning decides what the query's scan loads
            schema = _parquet_schema(paths)
        elif self._fmt == "orc":
            schema = _orc_schema(paths)
        elif self._fmt == "jdbc":
            from . import jdbc as _jdbc
            schema = _jdbc.table_schema(paths[0], self._options)
        else:
            schema = _load_batch(self._fmt, paths, self._options).schema
        rel = L.FileRelation(self._fmt, paths, schema, self._options)
        return DataFrame(self._session, rel)

    def parquet(self, *paths) -> "Any":
        return self.format("parquet").load(list(paths) if len(paths) > 1
                                           else paths[0])

    def orc(self, *paths) -> "Any":
        return self.format("orc").load(list(paths) if len(paths) > 1
                                       else paths[0])

    def csv(self, path, header=None, sep=None, inferSchema=None,
            nullValue=None) -> "Any":
        if header is not None:
            self.option("header", header)
        if sep is not None:
            self.option("sep", sep)
        if inferSchema is not None:
            self.option("inferschema", inferSchema)
        if nullValue is not None:
            self.option("nullvalue", nullValue)
        return self.format("csv").load(path)

    def json(self, path) -> "Any":
        return self.format("json").load(path)

    def text(self, path) -> "Any":
        return self.format("text").load(path)

    def jdbc(self, url: str, table: str = None, column: str = None,
             lowerBound=None, upperBound=None, numPartitions=None,
             predicates=None, properties=None) -> "Any":
        """Relational source over DB-API connections
        (`DataFrameReader.jdbc`, `JDBCRelation.columnPartition` stride
        partitioning).  `predicates` is a list of SQL strings, one read
        partition each; or (`column`, `lowerBound`, `upperBound`,
        `numPartitions`) stride-partitions a numeric column."""
        self.format("jdbc").option("url", url)
        if table is not None:
            self.option("dbtable", table)
        if column is not None:
            if lowerBound is None or upperBound is None \
                    or numPartitions is None:
                raise AnalysisException(
                    "jdbc partitioning requires column, lowerBound, "
                    "upperBound and numPartitions together")
            self.option("partitioncolumn", column)
            self.option("lowerbound", int(lowerBound))
            self.option("upperbound", int(upperBound))
            self.option("numpartitions", int(numPartitions))
        if predicates:
            self.option("predicates", "\x1f".join(predicates))
        for k, v in (properties or {}).items():
            self.option(k, v)
        return self.load(url)

    def table(self, name: str) -> "Any":
        return self._session.table(name)


# ---------------------------------------------------------------------------
# DataFrameWriter (`sql/DataFrameWriter.scala` analog)
# ---------------------------------------------------------------------------

class DataFrameWriter:
    def __init__(self, df):
        self._df = df
        self._fmt = "parquet"
        self._mode = "errorifexists"
        self._options: Dict[str, str] = {}
        self._partition_by: List[str] = []

    def format(self, source: str) -> "DataFrameWriter":
        self._fmt = source.lower()
        return self

    def mode(self, m: str) -> "DataFrameWriter":
        m = m.lower()
        if m not in ("overwrite", "append", "ignore", "error", "errorifexists"):
            raise AnalysisException(f"unknown save mode: {m}")
        self._mode = "errorifexists" if m == "error" else m
        return self

    def option(self, key: str, value) -> "DataFrameWriter":
        self._options[str(key).lower()] = str(value)
        return self

    def partitionBy(self, *cols: str) -> "DataFrameWriter":
        self._partition_by = list(cols)
        return self

    # -- save paths -------------------------------------------------------
    def _arrow_table(self, df):
        import pyarrow as pa
        batch = df._execute()
        schema = batch.schema
        rows = batch.to_pylist()
        cols = list(zip(*rows)) if rows else [[] for _ in schema.fields]
        arrays = []
        for field, col in zip(schema.fields, cols):
            arrays.append(pa.array(list(col), _engine_to_arrow(field.dataType)))
        return pa.table(dict(zip(schema.names, arrays)))

    def _prepare_dir(self, path: str) -> bool:
        """Returns False if the write should be skipped (ignore mode)."""
        if os.path.exists(path) and os.listdir(path):
            if self._mode == "errorifexists":
                raise AnalysisException(f"path {path} already exists")
            if self._mode == "ignore":
                return False
            if self._mode == "overwrite":
                import shutil
                shutil.rmtree(path)
        os.makedirs(path, exist_ok=True)
        return True

    def _next_part(self, path: str, ext: str) -> str:
        existing = len([f for f in os.listdir(path)
                        if f.startswith("part-")]) if os.path.exists(path) else 0
        return os.path.join(path, f"part-{existing:05d}{ext}")

    def _write_table(self, table, path: str, ext: str,
                     out: Optional[str] = None) -> None:
        import pyarrow as pa
        os.makedirs(path, exist_ok=True)
        if out is None:
            # batch writes pick the next free part slot; streaming sinks
            # pass an explicit deterministic target instead (idempotent
            # replay must overwrite, not append a new part)
            out = self._next_part(path, ext)
        if self._fmt == "parquet":
            import pyarrow.parquet as pq
            pq.write_table(table, out)
        elif self._fmt == "csv":
            import pyarrow.csv as pacsv
            header = str(self._options.get("header", "false")).lower() == "true"
            opts = pacsv.WriteOptions(include_header=header)
            pacsv.write_csv(table, out, opts)
        elif self._fmt == "json":
            with open(out, "w", encoding="utf-8") as f:
                for row in table.to_pylist():
                    f.write(_json.dumps(row, default=str) + "\n")
        elif self._fmt == "text":
            if table.num_columns != 1:
                raise AnalysisException("text format writes exactly 1 column")
            with open(out, "w", encoding="utf-8") as f:
                for v in table.columns[0].to_pylist():
                    f.write(("" if v is None else str(v)) + "\n")
        elif self._fmt == "orc":
            import pyarrow.orc as paorc
            paorc.write_table(table, out)
        else:
            raise AnalysisException(f"unsupported format: {self._fmt}")

    def save(self, path: str) -> None:
        ext = {"parquet": ".parquet", "csv": ".csv",
               "json": ".json", "text": ".txt", "orc": ".orc"}[self._fmt]
        if not self._prepare_dir(path):
            return
        table = self._arrow_table(self._df)
        if self._partition_by:
            import pyarrow as pa
            names = table.column_names
            for p in self._partition_by:
                if p not in names:
                    raise AnalysisException(f"partition column {p} not found")
            keep = [n for n in names if n not in self._partition_by]
            pydict = table.to_pydict()
            rows = list(zip(*[pydict[n] for n in names])) if table.num_rows \
                else []
            groups: Dict[tuple, List[tuple]] = {}
            for r in rows:
                key = tuple(r[names.index(p)] for p in self._partition_by)
                groups.setdefault(key, []).append(r)
            for key, grp in groups.items():
                sub = path
                for p, v in zip(self._partition_by, key):
                    sub = os.path.join(sub, f"{p}={v}")
                cols = list(zip(*grp))
                sub_table = pa.table({
                    n: pa.array(list(cols[names.index(n)]),
                                table.schema.field(n).type) for n in keep})
                self._write_table(sub_table, sub, ext)
        else:
            self._write_table(table, path, ext)
        open(os.path.join(path, "_SUCCESS"), "w").close()
        # DataFrame-API writes mutate the same paths the SQL commands do
        # (CREATE TABLE AS / INSERT INTO route through this writer): a
        # serving plan cache holding entries that READ this path would
        # replay stale capacities/CBO sides, so the write goes through
        # the same invalidation hook the SQL commands use
        session = getattr(self._df, "session", None)
        invalidate = getattr(session, "_invalidate_plan_cache", None)
        if invalidate is not None:
            invalidate(path=os.path.abspath(path))

    def parquet(self, path: str) -> None:
        self.format("parquet").save(path)

    def orc(self, path: str) -> None:
        self.format("orc").save(path)

    def csv(self, path: str, header=None) -> None:
        if header is not None:
            self.option("header", header)
        self.format("csv").save(path)

    def json(self, path: str) -> None:
        self.format("json").save(path)

    def text(self, path: str) -> None:
        self.format("text").save(path)

    def jdbc(self, url: str, table: str, mode: str = None,
             properties=None) -> None:
        """Write into a relational table over a DB-API connection
        (`DataFrameWriter.jdbc` / `JdbcUtils.saveTable`): DDL derived
        from the schema, rows in one batched-INSERT transaction."""
        from . import jdbc as _jdbc
        if mode is not None:
            self.mode(mode)
        opts = dict(self._options)
        for k, v in (properties or {}).items():
            opts[str(k).lower()] = str(v)
        _jdbc.write_table(self._arrow_table(self._df), url, table,
                          self._mode, opts)

    def saveAsTable(self, name: str) -> None:
        """Persist as a catalog table under the warehouse dir
        (`DataFrameWriter.saveAsTable`)."""
        self._df.session.catalog.save_table(
            name, self._df, self._fmt, self._mode, self._options,
            self._partition_by)
