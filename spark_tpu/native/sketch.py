"""BloomFilter / CountMinSketch (common/sketch analogs).

Native C++ kernels when the toolchain is available, numpy fallback
otherwise; both lanes share the Murmur3_x86_32 hashing convention of the
reference (`BloomFilterImpl.java`, `CountMinSketchImpl.java`), so results
are identical across lanes."""

from __future__ import annotations

import ctypes
import math

import numpy as np

from .build import load_library


def _u32(x) -> np.ndarray:
    return np.asarray(x).astype(np.uint32)


def _mixK1(k1):
    k1 = (k1 * np.uint32(0xcc9e2d51)).astype(np.uint32)
    k1 = ((k1 << np.uint32(15)) | (k1 >> np.uint32(17))).astype(np.uint32)
    return (k1 * np.uint32(0x1b873593)).astype(np.uint32)


def _mixH1(h1, k1):
    h1 = (h1 ^ k1).astype(np.uint32)
    h1 = ((h1 << np.uint32(13)) | (h1 >> np.uint32(19))).astype(np.uint32)
    return (h1 * np.uint32(5) + np.uint32(0xe6546b64)).astype(np.uint32)


def murmur3_hash_long(items, seed) -> np.ndarray:
    """Vectorized Murmur3_x86_32 hashLong; `seed` scalar or per-item
    array; returns int32 (bit-exact with the reference/native lane)."""
    items = np.asarray(items, np.int64)
    x = items.view(np.uint64)
    low = (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    high = (x >> np.uint64(32)).astype(np.uint32)
    h1 = _u32(np.broadcast_to(np.asarray(seed, np.int32), items.shape))
    h1 = _mixH1(h1, _mixK1(low))
    h1 = _mixH1(h1, _mixK1(high))
    h = (h1 ^ np.uint32(8)).astype(np.uint32)
    h ^= h >> np.uint32(16)
    h = (h * np.uint32(0x85ebca6b)).astype(np.uint32)
    h ^= h >> np.uint32(13)
    h = (h * np.uint32(0xc2b2ae35)).astype(np.uint32)
    h ^= h >> np.uint32(16)
    return h.view(np.int32)


def _probe_positions(items: np.ndarray, num_hashes: int,
                     num_bits: int) -> np.ndarray:
    """(n, k) bit positions, BloomFilterImpl double-hash scheme."""
    h1 = murmur3_hash_long(items, 0).astype(np.int32)
    h2 = murmur3_hash_long(items, h1).astype(np.int32)
    i = np.arange(1, num_hashes + 1, dtype=np.int32)
    combined = (h1[:, None] + i[None, :] * h2[:, None]).astype(np.int32)
    combined = np.where(combined < 0, ~combined, combined)
    return combined.astype(np.int64) % num_bits


class BloomFilter:
    """`util/sketch/BloomFilter.java` for int64 items."""

    def __init__(self, expected_items: int, fpp: float = 0.03):
        n = max(int(expected_items), 1)
        m = int(math.ceil(-n * math.log(fpp) / (math.log(2) ** 2)))
        self.num_bits = max((m + 63) // 64 * 64, 64)
        self.num_hashes = max(int(round(self.num_bits / n * math.log(2))), 1)
        self.bits = np.zeros(self.num_bits // 64, np.uint64)

    @staticmethod
    def create(expected_items: int, fpp: float = 0.03) -> "BloomFilter":
        return BloomFilter(expected_items, fpp)

    def put_long(self, items) -> None:
        items = np.atleast_1d(np.asarray(items, np.int64))
        lib = load_library()
        if lib is not None:
            lib.bloom_put_longs(
                self.bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                self.num_bits, self.num_hashes,
                np.ascontiguousarray(items).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)), len(items))
            return
        pos = _probe_positions(items, self.num_hashes, self.num_bits)
        np.bitwise_or.at(self.bits, pos.ravel() >> 6,
                         np.uint64(1) << (pos.ravel() & 63).astype(np.uint64))

    putLong = put_long

    def might_contain_long(self, items) -> np.ndarray:
        items = np.atleast_1d(np.asarray(items, np.int64))
        lib = load_library()
        if lib is not None:
            out = np.zeros(len(items), np.uint8)
            lib.bloom_might_contain_longs(
                self.bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                self.num_bits, self.num_hashes,
                np.ascontiguousarray(items).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)), len(items),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
            return out.astype(bool)
        pos = _probe_positions(items, self.num_hashes, self.num_bits)
        word = self.bits[pos >> 6]
        bit = (np.uint64(1) << (pos & 63).astype(np.uint64))
        return ((word & bit) != 0).all(axis=1)

    mightContainLong = might_contain_long

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        assert self.num_bits == other.num_bits \
            and self.num_hashes == other.num_hashes
        self.bits |= other.bits
        return self


class CountMinSketch:
    """`util/sketch/CountMinSketch.java` for int64 items."""

    def __init__(self, eps: float = 0.001, confidence: float = 0.99):
        self.width = int(math.ceil(2.0 / eps))
        self.depth = int(math.ceil(-math.log(1 - confidence) / math.log(2)))
        self.table = np.zeros((self.depth, self.width), np.int64)
        self.total = 0

    @staticmethod
    def create(eps: float = 0.001, confidence: float = 0.99
               ) -> "CountMinSketch":
        return CountMinSketch(eps, confidence)

    def _rows(self, items: np.ndarray) -> np.ndarray:
        seeds = np.arange(self.depth, dtype=np.int32)
        h = np.stack([murmur3_hash_long(items, int(s)) for s in seeds], 1)
        h = np.where(h < 0, ~h, h)
        return h.astype(np.int64) % self.width

    def add_long(self, items, count: int = 1) -> None:
        items = np.atleast_1d(np.asarray(items, np.int64))
        self.total += count * len(items)
        lib = load_library()
        if lib is not None:
            lib.cms_add_longs(
                self.table.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                self.depth, self.width,
                np.ascontiguousarray(items).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)), len(items), count)
            return
        cols = self._rows(items)
        for i in range(self.depth):
            np.add.at(self.table[i], cols[:, i], count)

    addLong = add_long

    def estimate_count(self, items) -> np.ndarray:
        items = np.atleast_1d(np.asarray(items, np.int64))
        lib = load_library()
        if lib is not None:
            out = np.zeros(len(items), np.int64)
            lib.cms_estimate_longs(
                self.table.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                self.depth, self.width,
                np.ascontiguousarray(items).ctypes.data_as(
                    ctypes.POINTER(ctypes.c_int64)), len(items),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
            return out
        cols = self._rows(items)
        ests = np.stack([self.table[i][cols[:, i]]
                         for i in range(self.depth)], 1)
        return ests.min(axis=1)

    estimateCount = estimate_count

    def merge(self, other: "CountMinSketch") -> "CountMinSketch":
        assert self.table.shape == other.table.shape
        self.table += other.table
        self.total += other.total
        return self
