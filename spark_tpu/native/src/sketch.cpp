// Host-side sketch kernels: Murmur3_x86_32, BloomFilter, CountMinSketch.
//
// The native equivalent of the reference's `common/sketch` package
// (`util/sketch/BloomFilterImpl.java`, `CountMinSketchImpl.java`) and the
// `Murmur3_x86_32.java` hash the JVM side leans on (SURVEY §2.11 native
// ledger).  Bit-exact with the Java implementations so sketches built here
// can interoperate with serialized reference sketches for longs.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in this image).
// Build: spark_tpu/native/build.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------
// Murmur3_x86_32 (public domain algorithm; layout matches the
// reference's hashLong/hashBytes conventions)
// ---------------------------------------------------------------------

static inline uint32_t rotl32(uint32_t x, int8_t r) {
    return (x << r) | (x >> (32 - r));
}

static inline uint32_t fmix32(uint32_t h) {
    h ^= h >> 16; h *= 0x85ebca6b;
    h ^= h >> 13; h *= 0xc2b2ae35;
    h ^= h >> 16;
    return h;
}

static inline uint32_t mixK1(uint32_t k1) {
    k1 *= 0xcc9e2d51; k1 = rotl32(k1, 15); k1 *= 0x1b873593;
    return k1;
}

static inline uint32_t mixH1(uint32_t h1, uint32_t k1) {
    h1 ^= k1; h1 = rotl32(h1, 13); h1 = h1 * 5 + 0xe6546b64;
    return h1;
}

// hashLong: two 32-bit halves, little-endian order (Murmur3_x86_32.java)
int32_t murmur3_hash_long(int64_t input, int32_t seed) {
    uint32_t low = (uint32_t)input;
    uint32_t high = (uint32_t)((uint64_t)input >> 32);
    uint32_t h1 = (uint32_t)seed;
    h1 = mixH1(h1, mixK1(low));
    h1 = mixH1(h1, mixK1(high));
    return (int32_t)fmix32(h1 ^ 8u);
}

int32_t murmur3_hash_bytes(const uint8_t* data, int32_t len, int32_t seed) {
    uint32_t h1 = (uint32_t)seed;
    int32_t nblocks = len / 4;
    for (int32_t i = 0; i < nblocks; i++) {
        uint32_t k1;
        std::memcpy(&k1, data + 4 * i, 4);
        h1 = mixH1(h1, mixK1(k1));
    }
    // tail: the reference hashes trailing bytes one at a time through
    // mixK1 WITHOUT mixH1 accumulation order differences — match
    // Murmur3_x86_32.hashUnsafeBytes (byte-at-a-time variant hashes each
    // remaining byte as its own int via mixK1/h1^=).
    for (int32_t i = nblocks * 4; i < len; i++) {
        uint32_t half = (uint32_t)(int32_t)(int8_t)data[i];
        h1 ^= mixK1(half);
    }
    return (int32_t)fmix32(h1 ^ (uint32_t)len);
}

// ---------------------------------------------------------------------
// BloomFilter (BloomFilterImpl.putLong/mightContainLong semantics:
// h1 = murmur(seed 0), h2 = murmur(seed h1), k probes (h1 + i*h2))
// ---------------------------------------------------------------------

void bloom_put_longs(uint64_t* bits, int64_t num_bits, int32_t num_hashes,
                     const int64_t* items, int64_t n) {
    for (int64_t j = 0; j < n; j++) {
        int32_t h1 = murmur3_hash_long(items[j], 0);
        int32_t h2 = murmur3_hash_long(items[j], h1);
        for (int32_t i = 1; i <= num_hashes; i++) {
            int32_t combined = h1 + i * h2;
            if (combined < 0) combined = ~combined;
            int64_t bit = combined % num_bits;
            bits[bit >> 6] |= (1ull << (bit & 63));
        }
    }
}

void bloom_might_contain_longs(const uint64_t* bits, int64_t num_bits,
                               int32_t num_hashes, const int64_t* items,
                               int64_t n, uint8_t* out) {
    for (int64_t j = 0; j < n; j++) {
        int32_t h1 = murmur3_hash_long(items[j], 0);
        int32_t h2 = murmur3_hash_long(items[j], h1);
        uint8_t hit = 1;
        for (int32_t i = 1; i <= num_hashes && hit; i++) {
            int32_t combined = h1 + i * h2;
            if (combined < 0) combined = ~combined;
            int64_t bit = combined % num_bits;
            if (!(bits[bit >> 6] & (1ull << (bit & 63)))) hit = 0;
        }
        out[j] = hit;
    }
}

// ---------------------------------------------------------------------
// CountMinSketch (CountMinSketchImpl addLong/estimateCount: row i uses
// hash(item, seed=i) % width)
// ---------------------------------------------------------------------

void cms_add_longs(int64_t* table, int32_t depth, int32_t width,
                   const int64_t* items, int64_t n, int64_t count) {
    for (int64_t j = 0; j < n; j++) {
        for (int32_t i = 0; i < depth; i++) {
            int32_t h = murmur3_hash_long(items[j], i);
            if (h < 0) h = ~h;
            table[(int64_t)i * width + (h % width)] += count;
        }
    }
}

void cms_estimate_longs(const int64_t* table, int32_t depth, int32_t width,
                        const int64_t* items, int64_t n, int64_t* out) {
    for (int64_t j = 0; j < n; j++) {
        int64_t best = INT64_MAX;
        for (int32_t i = 0; i < depth; i++) {
            int32_t h = murmur3_hash_long(items[j], i);
            if (h < 0) h = ~h;
            int64_t v = table[(int64_t)i * width + (h % width)];
            if (v < best) best = v;
        }
        out[j] = best;
    }
}

// ---------------------------------------------------------------------
// k-way merge of sorted int64 runs (the external-sort merge kernel the
// multibatch spill path uses: UnsafeExternalSorter.java's merge step)
// Runs are concatenated in `keys`; `offsets` has k+1 entries.  Emits the
// permutation of global indices in ascending key order (stable across
// runs in offset order).
// ---------------------------------------------------------------------

void merge_sorted_runs(const int64_t* keys, const int64_t* offsets,
                       int32_t k, int64_t* out_perm) {
    // simple binary-heap merge
    struct Node { int64_t key; int32_t run; int64_t pos; };
    Node* heap = new Node[k];
    int32_t sz = 0;
    auto less = [](const Node& a, const Node& b) {
        return a.key < b.key || (a.key == b.key && a.run < b.run);
    };
    auto push = [&](Node nd) {
        int32_t i = sz++;
        heap[i] = nd;
        while (i > 0) {
            int32_t p = (i - 1) / 2;
            if (less(heap[i], heap[p])) {
                Node t = heap[i]; heap[i] = heap[p]; heap[p] = t;
                i = p;
            } else break;
        }
    };
    auto pop = [&]() {
        Node top = heap[0];
        heap[0] = heap[--sz];
        int32_t i = 0;
        for (;;) {
            int32_t l = 2 * i + 1, r = 2 * i + 2, m = i;
            if (l < sz && less(heap[l], heap[m])) m = l;
            if (r < sz && less(heap[r], heap[m])) m = r;
            if (m == i) break;
            Node t = heap[i]; heap[i] = heap[m]; heap[m] = t;
            i = m;
        }
        return top;
    };
    for (int32_t r = 0; r < k; r++)
        if (offsets[r] < offsets[r + 1])
            push(Node{keys[offsets[r]], r, offsets[r]});
    int64_t w = 0;
    while (sz > 0) {
        Node nd = pop();
        out_perm[w++] = nd.pos;
        int64_t nxt = nd.pos + 1;
        if (nxt < offsets[nd.run + 1])
            push(Node{keys[nxt], nd.run, nxt});
    }
    delete[] heap;
}

}  // extern "C"

// ---------------------------------------------------------------------
// Grace-join host partitioner: stable counting sort of row indices by
// bucket id (the host half of the grace hash join's partition phase,
// `sql/stages.py`; the role ShuffleExchange's hash partitioner plays in
// `core/.../shuffle/sort/ShuffleExternalSorter.java`).  O(n + buckets)
// vs argsort's O(n log n), one pass over the ids.
// ---------------------------------------------------------------------

extern "C" void partition_permutation(const int64_t* bucket_ids, int64_t n,
                                      int64_t n_buckets, int64_t* perm,
                                      int64_t* bounds /* n_buckets+1 */) {
    for (int64_t b = 0; b <= n_buckets; ++b) bounds[b] = 0;
    for (int64_t i = 0; i < n; ++i) {
        int64_t b = bucket_ids[i];
        if (b < 0) b = 0;
        if (b >= n_buckets) b = n_buckets - 1;
        bounds[b + 1]++;
    }
    for (int64_t b = 0; b < n_buckets; ++b) bounds[b + 1] += bounds[b];
    // cursor starts at each bucket's begin offset; stable fill
    int64_t* cursor = new int64_t[n_buckets];
    for (int64_t b = 0; b < n_buckets; ++b) cursor[b] = bounds[b];
    for (int64_t i = 0; i < n; ++i) {
        int64_t b = bucket_ids[i];
        if (b < 0) b = 0;
        if (b >= n_buckets) b = n_buckets - 1;
        perm[cursor[b]++] = i;
    }
    delete[] cursor;
}
