"""Native k-way merge of sorted runs (external-sort merge kernel).

The `UnsafeExternalSorter.java` merge step: spilled sorted runs merge on
the host by int64 sort key.  C++ heap merge when available, numpy
mergesort fallback (stable across runs in offset order either way)."""

from __future__ import annotations

import ctypes
from typing import Sequence

import numpy as np

from .build import load_library


def merge_sorted_runs(run_keys: Sequence[np.ndarray]) -> np.ndarray:
    """Global ascending-order permutation over concatenated runs.

    Each entry of `run_keys` must already be sorted ascending; the result
    indexes into their concatenation, ties broken by run order (stable)."""
    runs = [np.ascontiguousarray(np.asarray(r, np.int64)) for r in run_keys]
    keys = np.concatenate(runs) if runs else np.zeros(0, np.int64)
    offsets = np.zeros(len(runs) + 1, np.int64)
    np.cumsum([len(r) for r in runs], out=offsets[1:])
    lib = load_library()
    if lib is not None:
        out = np.zeros(len(keys), np.int64)
        lib.merge_sorted_runs(
            keys.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(runs),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        return out
    # fallback: stable mergesort over (key, position) — positions are
    # already grouped by run, so stability gives run-order ties
    return np.argsort(keys, kind="stable").astype(np.int64)
