"""Native host partitioner for the grace-join partition phase.

Stable counting sort of row indices by bucket id — the host half of the
hash partition step (`ShuffleExternalSorter.java`'s role on the
spill path).  C++ single pass when available, stable argsort fallback.
"""

from __future__ import annotations

import ctypes
from typing import Tuple

import numpy as np

from .build import load_library


def partition_permutation(bucket_ids: np.ndarray, n_buckets: int
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """(perm, bounds): ``perm`` orders row indices by bucket (stable);
    bucket b's rows are ``perm[bounds[b]:bounds[b+1]]``."""
    ids = np.ascontiguousarray(np.asarray(bucket_ids, np.int64))
    n = len(ids)
    lib = load_library()
    if lib is not None:
        perm = np.zeros(n, np.int64)
        bounds = np.zeros(n_buckets + 1, np.int64)
        p = ctypes.POINTER(ctypes.c_int64)
        lib.partition_permutation(
            ids.ctypes.data_as(p), n, n_buckets,
            perm.ctypes.data_as(p), bounds.ctypes.data_as(p))
        return perm, bounds
    order = np.argsort(ids, kind="stable").astype(np.int64)
    bounds = np.searchsorted(ids[order],
                             np.arange(n_buckets + 1)).astype(np.int64)
    return order, bounds
