"""Compile-on-first-use loader for the native kernel library."""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
import threading
from typing import Optional

_SRC = os.path.join(os.path.dirname(__file__), "src", "sketch.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _cache_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    d = os.environ.get("SPARK_TPU_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(), "spark_tpu_native")
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f"spark_tpu_native_{digest}.so")


def load_library() -> Optional[ctypes.CDLL]:
    """The compiled library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            so = _cache_path()
            if not os.path.exists(so):
                tmp = so + f".build-{os.getpid()}"
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)     # atomic vs concurrent builders
            lib = ctypes.CDLL(so)
            _sign(lib)
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def native_available() -> bool:
    return load_library() is not None


def _sign(lib: ctypes.CDLL) -> None:
    i32, i64 = ctypes.c_int32, ctypes.c_int64
    p_i64 = ctypes.POINTER(i64)
    p_u64 = ctypes.POINTER(ctypes.c_uint64)
    p_u8 = ctypes.POINTER(ctypes.c_uint8)
    lib.murmur3_hash_long.restype = i32
    lib.murmur3_hash_long.argtypes = [i64, i32]
    lib.bloom_put_longs.restype = None
    lib.bloom_put_longs.argtypes = [p_u64, i64, i32, p_i64, i64]
    lib.bloom_might_contain_longs.restype = None
    lib.bloom_might_contain_longs.argtypes = [p_u64, i64, i32, p_i64, i64,
                                              p_u8]
    lib.cms_add_longs.restype = None
    lib.cms_add_longs.argtypes = [p_i64, i32, i32, p_i64, i64, i64]
    lib.cms_estimate_longs.restype = None
    lib.cms_estimate_longs.argtypes = [p_i64, i32, i32, p_i64, i64, p_i64]
    lib.merge_sorted_runs.restype = None
    lib.merge_sorted_runs.argtypes = [p_i64, p_i64, i32, p_i64]
    lib.partition_permutation.restype = None
    lib.partition_permutation.argtypes = [p_i64, i64, i64, p_i64, p_i64]
