"""Native (C++) host kernels, loaded via ctypes.

The runtime-side native layer the reference implements in
Java-on-Unsafe/JNI (`common/sketch`, `common/unsafe`, the external-sort
merge in `UnsafeExternalSorter.java`): compiled once per machine with g++
into a cached shared object.  Every entry point has a numpy fallback so
the engine still works where no toolchain exists (`native_available()`
reports which lane is active).
"""

from .build import load_library, native_available       # noqa: F401
from .sketch import BloomFilter, CountMinSketch         # noqa: F401
from .merge import merge_sorted_runs                    # noqa: F401
