"""SQL-over-HTTP serving endpoint with multi-session support.

The serving role of the reference's `sql/hive-thriftserver` (HiveServer2:
`HiveThriftServer2.scala`, per-connection session handles in
`SparkSQLSessionManager.scala`, statement lifecycle + cancellation in
`SparkExecuteStatementOperation.scala:77`) re-based on the one wire
format every client already speaks: POST a SQL string, receive JSON rows.

Concurrency model: a bounded worker pool executes statements; each
server session wraps its own ``SparkSession.newSession()`` (isolated
temp views / conf — the Thrift session handle analog) with a per-session
lock making it single-writer, so DIFFERENT sessions run in parallel
while one session's statements stay serial.  Cancellation is
cooperative, like the reference's task interruption: streamed executions
check a session flag between batches.

Multi-tenancy guards (serving/ package):

* every submission passes an ``AdmissionController`` BEFORE anything is
  registered — over global-concurrency, per-session-queue, or
  host-memory limits the client gets a structured 429 with Retry-After,
  never an unbounded queue entry;
* all server sessions share one ``PlanCache`` mapping optimized-plan
  fingerprints to compiled executables, so session B skips trace+compile
  for a statement session A already ran (responses carry ``cacheHit`` /
  ``planningSkippedMs``);
* per-statement deadlines (``spark.tpu.server.statementTimeout``) ride
  the cooperative-cancel machinery, and idle sessions are reaped after
  ``spark.tpu.server.sessionTimeout`` seconds.

    python -m spark_tpu.server --port 8123 --workers 4 &
    curl -d 'SELECT 1 AS x' localhost:8123/sql

Endpoints (Authorization: Bearer <token> required when a token is set
via --token or SPARK_TPU_SERVER_TOKEN):
    POST   /session             → {"sessionId"} (isolated temp views)
    DELETE /session/<id>        close a session
    POST   /sql                 body = SQL text or JSON {"query", ...,
                                "session": sid, "id": statement-id}
                                (or X-Session-Id / X-Statement-Id
                                headers) → {"columns", "rows",
                                "rowCount", "durationMs", "statementId",
                                "cacheHit", "planningSkippedMs"};
                                429 + Retry-After when admission rejects
    POST   /cancel              {"id": statement-id} → cooperative
                                cancel; queued statements are removed
                                from their session FIFO immediately
    GET    /statement/<id>      statement status (running/done/...)
    POST   /stream              register a STANDING incremental query:
                                {"session", "source": {"format", "path",
                                "schema"?, "options"?}, "select"?,
                                "sink": {"format", "path"}, "mode"?,
                                "checkpoint"?, "interval"?} →
                                {"streamId"}; the query is an admission
                                tenant (429 + Retry-After over
                                maxStandingQueries / headroom) and its
                                session is never idle-reaped while it
                                lives
    GET    /stream/<id>         standing-query status: batch id, commit/
                                replay/spill/watermark metrics, last
                                progress, deferral Retry-After
    DELETE /stream/<id>         stop a standing query, release its slot
    GET    /status              version, sessions, statements, per-
                                session queue depths, standing queries,
                                admission counters, plan-cache stats
"""

from __future__ import annotations

import collections
import hmac
import json
import os
import re
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

from . import config as C
from .metrics import Source
from .serving import AdmissionController, AdmissionRejected, PlanCache

__all__ = ["SQLServer"]


_SQL_LITERALS = re.compile(r"'(?:[^']|'')*'|\b\d+(?:\.\d+)?\b")
_SQL_WS = re.compile(r"\s+")


def _cost_key(text: str) -> str:
    """Query-shape key for per-shape admission cost estimates: the
    statement with literals blanked and whitespace collapsed, so
    ``WHERE id = 7`` and ``WHERE id = 9`` share one duration history
    while a full-table scan keeps its own."""
    return _SQL_WS.sub(" ", _SQL_LITERALS.sub("?", text)).strip().lower()


def _json_safe(v: Any):
    if isinstance(v, float):
        # RFC 8259 has no NaN/Infinity literals; strict clients reject them
        if v != v:
            return None
        if v in (float("inf"), float("-inf")):
            return str(v)
        return v
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return str(v)


class _ServerSession:
    """One Thrift-session-handle analog: an isolated SparkSession plus the
    lock that makes it single-writer."""

    def __init__(self, session):
        self.session = session
        self.lock = threading.Lock()
        self.created = time.time()
        self.last_used = self.created
        # id of the statement currently executing on this session, guarded
        # by the server's _reg_lock: /cancel must only interrupt the
        # session when ITS target is the one running, not whatever
        # statement happens to hold the session lock by then
        self.running_stmt: Optional[str] = None
        # FIFO of (stmt, future, work) triples waiting on this session,
        # guarded by the server's _reg_lock.  A busy session drains its
        # queue on ONE pool slot (``draining`` marks the drainer alive) —
        # N statements stacked on one session must never pin N workers
        # while other sessions starve
        self.queue: collections.deque = collections.deque()
        self.draining = False
        # standing (streaming) queries registered on this session, keyed
        # by stream id — a session carrying one is ALWAYS live for the
        # idle reaper, however long since its last statement
        self.streams: Dict[str, Any] = {}


class _Statement:
    def __init__(self, stmt_id: str, session_id: str, query: str):
        self.id = stmt_id
        self.session_id = session_id
        self.query = query
        self.status = "queued"          # queued|running|done|error|cancelled
        self.cancel_requested = False
        self.submitted = time.time()


class SQLServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 8123,
                 workers: int = 4, token: Optional[str] = None,
                 max_sessions: int = 64):
        self.session = session           # default/shared session
        self.host = host
        self.port = port
        self.token = token if token is not None \
            else os.environ.get("SPARK_TPU_SERVER_TOKEN") or None
        self.max_sessions = max_sessions
        self._default = _ServerSession(session)
        self._sessions: Dict[str, _ServerSession] = {}
        self._statements: Dict[str, _Statement] = {}
        self._reg_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(max_workers=max(workers, 1),
                                        thread_name_prefix="sql-worker")
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # -- multi-tenant serving core: shared across ALL sessions -------
        self._admission = AdmissionController(
            session.conf_obj,
            lambda: getattr(session, "_host_ledger", None),
            grace_supplier=self._grace_total,
            blockstore_supplier=lambda: getattr(
                getattr(getattr(session, "_crossproc_svc", None),
                        "blockclient", None), "store", None),
            queued_supplier=self._queued_total)
        self._plan_cache: Optional[PlanCache] = None
        if session.conf_obj.get(C.SERVER_PLAN_CACHE_ENABLED):
            self._plan_cache = PlanCache(session.conf_obj)
        # the default session executes through the shared cache too
        session._plan_cache = self._plan_cache
        # ONE StatsFeedback serves every session: observed exchange
        # cardinalities from any statement feed later statements'
        # choose_join_strategy server-wide (a repeated misestimated join
        # plans broadcast on its second run, whichever session runs it)
        from .parallel.crossproc import StatsFeedback
        self._stats_feedback = StatsFeedback()
        session._stats_feedback = self._stats_feedback
        self._sessions_expired = 0
        self._statement_readmits = 0     # transparent recovery re-admits
        self._stream_retry: Dict[str, float] = {}  # last deferral hints
        self._reaper_stop = threading.Event()
        self._reaper: Optional[threading.Thread] = None
        # block-service lifecycle (started/stopped with the server): when
        # the shared session runs a block-service-backed shuffle, the
        # serving tier owns the orphan reaper — elastic worker reap/spawn
        # leaves exchange/state orphans only the service may delete
        self._blockserver = None
        # elastic worker pool (started with the server when
        # spark.tpu.server.pool.enabled): admission demand drives
        # spawn/reap of real worker processes over the block service
        self._pool_supervisor = None
        self._register_metrics()

    # -- grace-degradation visibility ------------------------------------
    @staticmethod
    def _grace_stats(session) -> Dict[str, int]:
        """One session's cumulative grace-mode activity, read off its
        host-shuffle service counters (empty when host shuffle is off or
        the session never degraded)."""
        svc = getattr(session, "_crossproc_svc", None)
        counters = getattr(svc, "counters", None) if svc is not None \
            else None
        if not counters:
            return {}
        out = {k: int(counters.get(k, 0))
               for k in ("grace_buckets_used", "grace_spill_bytes",
                         "grace_salted_resplits", "reducers_elastic")}
        return out if any(out.values()) else {}

    # -- exchange-tier visibility ----------------------------------------
    @staticmethod
    def _ici_stats(session) -> Dict[str, int]:
        """One session's cumulative ICI device-tier activity (sides
        shipped HBM→HBM, raw bytes moved, device attempts folded back
        onto the host/DCN tier, agreed intra-domain peer count); empty
        when host shuffle is off or the device tier never engaged."""
        svc = getattr(session, "_crossproc_svc", None)
        counters = getattr(svc, "counters", None) if svc is not None \
            else None
        if not counters:
            return {}
        out = {k: int(counters.get(k, 0))
               for k in ("ici_exchanges", "ici_bytes_moved",
                         "dcn_fallback_exchanges", "tier_split_peers")}
        return out if any(out.values()) else {}

    # -- run-length execution visibility ----------------------------------
    @staticmethod
    def _run_stats(session) -> Dict[str, int]:
        """One session's cumulative run-length/delta execution activity
        (columns shipped encoded, wire bytes saved, rows processed by
        run-aware operators, rows re-inflated at materialization
        boundaries); empty when host shuffle is off or run codes never
        engaged.  The two row counters are module-wide, so they diff
        against the service's birth snapshot — same math its shuffle
        metrics Source uses."""
        svc = getattr(session, "_crossproc_svc", None)
        counters = getattr(svc, "counters", None) if svc is not None \
            else None
        if not counters:
            return {}
        from . import columnar as _col
        out = {k: int(counters.get(k, 0))
               for k in ("rle_columns_encoded", "run_bytes_saved")}
        out["run_aware_op_rows"] = max(
            0, _col.run_aware_op_rows()
            - int(getattr(svc, "_run_aware_base", 0)))
        out["runs_materialized"] = max(
            0, _col.runs_materialized()
            - int(getattr(svc, "_runs_mat_base", 0)))
        out["run_plane_stages"] = max(
            0, _col.run_plane_stages()
            - int(getattr(svc, "_plane_stage_base", 0)))
        out["run_plane_rows"] = max(
            0, _col.run_plane_rows()
            - int(getattr(svc, "_plane_rows_base", 0)))
        out["run_plane_overflows"] = max(
            0, _col.run_plane_overflows()
            - int(getattr(svc, "_plane_ovf_base", 0)))
        out["run_plane_expansions"] = max(
            0, _col.run_plane_expansions()
            - int(getattr(svc, "_plane_exp_base", 0)))
        return out if any(out.values()) else {}

    def _queued_total(self) -> int:
        """Total statements waiting on session FIFOs tier-wide — the
        ``queued`` component of the admission demand signal.  Takes only
        ``_reg_lock``; the admission controller consults it OUTSIDE its
        own lock."""
        try:
            with self._reg_lock:
                sessions = [self._default] + list(self._sessions.values())
                return sum(len(ss.queue) for ss in sessions)
        except Exception:
            return 0

    def _grace_total(self) -> int:
        """Cumulative grace-degradation events across every session —
        the admission controller's learned signal that running near the
        headroom floor now costs spill-speed joins."""
        try:
            with self._reg_lock:
                sessions = [ss.session for ss in self._sessions.values()]
            sessions.append(self.session)
            return sum(
                self._grace_stats(s).get("grace_buckets_used", 0)
                for s in sessions)
        except Exception:
            return 0

    def _register_metrics(self) -> None:
        gauges = dict(self._admission.metrics_source())
        if self._plan_cache is not None:
            gauges.update(self._plan_cache.metrics_source())
        gauges["sessions_open"] = lambda: len(self._sessions)
        gauges["sessions_expired"] = lambda: self._sessions_expired
        gauges["statement_readmits"] = lambda: self._statement_readmits
        # block-service lifecycle: whether the tier runs the reaper, and
        # its lifetime reclaim total (0 until start() attaches one)
        gauges["blockserver_attached"] = (
            lambda: int(self._blockserver is not None))
        gauges["blockserver_gc_runs"] = lambda: (
            self._blockserver.gc_runs if self._blockserver else 0)
        ms = self.session.metricsSystem
        # re-registering (e.g. a second SQLServer on the same session)
        # replaces rather than duplicates the sources
        ms._sources = [s for s in ms._sources
                       if s.name not in ("serving", "pool")]
        ms.register_source(Source("serving", gauges))

        # elastic-pool gauges read through the supervisor handle so they
        # are live the moment start() attaches one (0 until then)
        def _pool_counter(name):
            def get():
                sup = self._pool_supervisor
                return sup.counters.get(name, 0) if sup else 0
            return get

        pool_gauges = {k: _pool_counter(k) for k in (
            "workers_spawned", "workers_reaped", "pool_target",
            "pool_live", "scale_decisions", "spawn_failures")}
        ms.register_source(Source("pool", pool_gauges))

    # -- session registry ------------------------------------------------
    def _open_session(self) -> str:
        with self._reg_lock:
            if len(self._sessions) >= self.max_sessions:
                raise RuntimeError(
                    f"session limit {self.max_sessions} reached")
            sess = self.session.newSession()
            sess._plan_cache = self._plan_cache   # shared plan→executable
            sess._stats_feedback = self._stats_feedback  # shared stats
            # one standing-query registry across the whole tier: the root
            # session's ``streaming`` metrics Source must see every
            # session's execs, so all sessions share the root's list
            if getattr(self.session, "_stream_execs", None) is None:
                self.session._stream_execs = []
            sess._stream_execs = self.session._stream_execs
            sid = uuid.uuid4().hex[:16]
            self._sessions[sid] = _ServerSession(sess)
        return sid

    def _close_session(self, sid: str) -> bool:
        with self._reg_lock:
            ss = self._sessions.pop(sid, None)
        if ss is None:
            return False
        self._release_session_streams(ss)
        ss.session.cancelAllQueries()
        ss.session._plan_cache = None
        return True

    def _release_session_streams(self, ss: _ServerSession) -> None:
        """Stop a departing session's standing queries and give their
        admission slots back — closing a session must not leak tenancy."""
        for stream_id, q in list(ss.streams.items()):
            try:
                q.stop()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                pass
            self._stream_retry.pop(stream_id, None)
            self._admission.unregister_stream()
        ss.streams.clear()

    def _resolve(self, sid: Optional[str]) -> _ServerSession:
        if not sid:
            return self._default
        ss = self._sessions.get(sid)
        if ss is None:
            raise KeyError(f"no such session {sid!r}")
        return ss

    def _expire_idle_sessions(self, now: Optional[float] = None) -> int:
        """Evict sessions idle longer than spark.tpu.server.sessionTimeout
        seconds.  Sessions with queued or running work are never touched —
        eviction must not lose admitted statements — and neither are
        sessions carrying a registered STANDING query: a stream triggers
        between client requests, so last_used alone says nothing about
        liveness (reaping it would kill an admitted tenant mid-protocol).
        Returns the count."""
        ttl = float(self.session.conf_obj.get(C.SERVER_SESSION_TIMEOUT))
        if ttl <= 0:
            return 0
        if now is None:
            now = time.time()
        with self._reg_lock:
            victims = [(sid, ss) for sid, ss in self._sessions.items()
                       if not ss.queue and not ss.draining
                       and ss.running_stmt is None
                       and not ss.streams
                       and now - ss.last_used > ttl]
            for sid, _ss in victims:
                self._sessions.pop(sid, None)
            self._sessions_expired += len(victims)
        for _sid, ss in victims:
            ss.session.cancelAllQueries()
            ss.session._plan_cache = None
        return len(victims)

    def _reap_loop(self) -> None:
        while not self._reaper_stop.wait(5.0):
            try:
                self._expire_idle_sessions()
            except Exception:   # noqa: BLE001 — the reaper must survive
                pass

    # -- standing queries -------------------------------------------------
    def _start_stream(self, payload: Dict[str, Any]) -> dict:
        """Register a standing incremental query on a server session.

        The query is a long-lived admission TENANT: ``register_stream``
        takes a slot (429 + Retry-After over
        ``spark.tpu.server.maxStandingQueries`` or under the grace-scaled
        headroom floor) held until DELETE /stream/<id>, and every
        micro-batch then passes the non-raising batch gate — a deferred
        batch leaves no WAL entry, so deferral never dents exactly-once.

        Spec: ``{"session": sid?, "source": {"format", "path", "schema"?,
        "options"?}, "select": [cols]?, "sink": {"format", "path"},
        "mode"?, "checkpoint"?, "interval"?}``."""
        ss = self._resolve(payload.get("session"))
        src = payload.get("source") or {}
        sink = payload.get("sink") or {}
        if not src.get("path") or not sink.get("path"):
            raise ValueError("stream spec needs source.path and sink.path")
        # the slot is taken BEFORE anything starts: a rejected standing
        # query leaves no thread, no checkpoint dir, no registry entry
        self._admission.register_stream()
        try:
            reader = ss.session.readStream.format(
                src.get("format", "json"))
            if src.get("schema"):
                reader = reader.schema(src["schema"])
            for k, v in (src.get("options") or {}).items():
                reader = reader.option(k, v)
            df = reader.load(src.get("path"))
            if payload.get("select"):
                df = df.select(*payload["select"])
            w = (df.writeStream.format(sink.get("format", "json"))
                 .outputMode(payload.get("mode", "append")))
            if payload.get("checkpoint"):
                w = w.option("checkpointLocation", payload["checkpoint"])
            w = w.trigger(
                processingTime=f"{float(payload.get('interval', 0.5))} "
                               "seconds")
            q = w.start(sink.get("path"))
        except Exception:
            self._admission.unregister_stream()
            raise
        return self.adopt_stream(payload.get("session"), q)

    def adopt_stream(self, sid: Optional[str], q) -> dict:
        """Wire an already-started StreamingQuery into the serving tier:
        batch-admission gate + session stream registry (reaper
        protection).  The programmatic entry point for embedding servers;
        the caller (or ``_start_stream``) owns the admission slot."""
        ss = self._resolve(sid)
        ex = q._ex
        key = f"stream:{ex.id[:8]}"

        def gate() -> bool:
            try:
                self._admission.admit_stream_batch(cost_key=key)
                self._stream_retry.pop(ex.id, None)
                return True
            except AdmissionRejected as e:
                # remembered so GET /stream/<id> can surface the hint the
                # trigger loop acted on
                self._stream_retry[ex.id] = e.retry_after_s
                return False

        ex._batch_admit = gate
        with self._reg_lock:
            ss.streams[ex.id] = q
        ss.last_used = time.time()
        return {"streamId": ex.id, "name": ex.name}

    def _find_stream(self, stream_id: str):
        with self._reg_lock:
            pool = [self._default] + list(self._sessions.values())
            for ss in pool:
                if stream_id in ss.streams:
                    return ss, ss.streams[stream_id]
        raise KeyError(f"no such stream {stream_id!r}")

    def _stream_status(self, stream_id: str) -> dict:
        _ss, q = self._find_stream(stream_id)
        ex = q._ex
        out = {"streamId": ex.id, "name": ex.name, "active": q.isActive,
               "batchId": ex.batch_id, "metrics": dict(ex.metrics),
               "lastProgress": q.lastProgress}
        if ex.exception is not None:
            out["error"] = \
                f"{type(ex.exception).__name__}: {ex.exception}"[:2000]
        retry = self._stream_retry.get(ex.id)
        if retry is not None:
            out["retryAfterSeconds"] = round(retry, 1)
        return out

    def _stop_stream(self, stream_id: str) -> dict:
        ss, q = self._find_stream(stream_id)
        q.stop()
        with self._reg_lock:
            ss.streams.pop(stream_id, None)
        self._stream_retry.pop(stream_id, None)
        self._admission.unregister_stream()
        ss.last_used = time.time()
        return {"stopped": stream_id,
                "batchesCommitted": q._ex.metrics["batches_committed"]}

    # -- statement execution ---------------------------------------------
    def _run_sql(self, text: str, sid: Optional[str],
                 stmt_id: Optional[str]) -> dict:
        from .parallel.hostshuffle import ExchangeFetchFailed

        ss = self._resolve(sid)          # unknown session → 404, nothing
        cost_key = _cost_key(text)
        # admission BEFORE registration: a rejected statement leaves no
        # trace — no registry entry, no queue slot, no partial execution
        with self._reg_lock:
            depth = len(ss.queue) + \
                (1 if (ss.running_stmt or ss.draining) else 0)
        # raises AdmissionRejected → 429; a known shape's Retry-After
        # comes from ITS duration history, not the global EWMA
        self._admission.admit(depth, cost_key=cost_key)
        admit_t = time.time()
        try:
            try:
                return self._run_admitted(ss, text, sid, stmt_id)
            except ExchangeFetchFailed:
                # a worker died and the in-query lineage recovery
                # exhausted its budget (or was disabled): the exchange
                # plane has already agreed the loss and blacklisted the
                # peer, so ONE transparent re-admit runs the statement
                # over the surviving live set.  Idempotent by the data
                # plane's contract — statements read, or write behind
                # the commit-marker rename.  Exactly once: a second
                # fetch failure surfaces to the client.
                with self._reg_lock:
                    self._statement_readmits += 1
                return self._run_admitted(ss, text, sid, stmt_id)
        finally:
            # release feeds the EWMAs behind Retry-After with end-to-end
            # (queue + execute) latency — what a retrying client sees
            self._admission.release(time.time() - admit_t,
                                    cost_key=cost_key)

    def _offloadable(self, ss: _ServerSession, text: str) -> bool:
        """Pool-eligible statements: plain SELECTs against PERSISTENT
        tables only — a session temp view lives in this process's
        memory, a pool worker cannot see it, and anything non-SELECT may
        mutate catalog state the session expects to observe."""
        if self._pool_supervisor is None:
            return False
        if not ss.session.conf_obj.get(C.SERVER_POOL_OFFLOAD):
            return False
        if ss.session.catalog._views:
            return False
        return text.strip().lower().startswith("select")

    def _run_admitted(self, ss: _ServerSession, text: str,
                      sid: Optional[str], stmt_id: Optional[str]) -> dict:
        from .sql.session import QueryCancelled

        if self._offloadable(ss, text):
            # any miss (no live worker, timeout, worker error) returns
            # None and the statement falls through to the local FIFO —
            # offload never makes a result worse than pool-off
            out = self._pool_supervisor.execute(text)
            if out is not None:
                out.setdefault("statementId",
                               stmt_id or uuid.uuid4().hex[:16])
                ss.last_used = time.time()
                return out

        stmt = _Statement(stmt_id or uuid.uuid4().hex[:16], sid or "", text)
        with self._reg_lock:
            if stmt.id in self._statements and \
                    self._statements[stmt.id].status in ("queued", "running"):
                raise RuntimeError(f"statement id {stmt.id!r} already active")
            self._statements[stmt.id] = stmt
            self._evict_statements()
        ss.last_used = time.time()

        def work() -> dict:
            with ss.lock:                # session state is single-writer
                # order matters vs /cancel: the flag clears BEFORE the
                # status becomes observable as "running", and a cancel
                # that raced in is honored by the re-check after — a
                # /cancel acknowledged with 200 is never lost
                ss.session.clear_cancel()
                with self._reg_lock:
                    stmt.status = "running"
                    ss.running_stmt = stmt.id
                timer: Optional[threading.Timer] = None
                try:
                    if stmt.cancel_requested:
                        stmt.status = "cancelled"
                        raise QueryCancelled("cancelled before execution")
                    timeout_s = float(
                        ss.session.conf_obj.get(C.SERVER_STATEMENT_TIMEOUT))
                    if timeout_s > 0:
                        waited = time.time() - stmt.submitted
                        if waited >= timeout_s:
                            stmt.status = "cancelled"
                            raise QueryCancelled(
                                f"statement deadline {timeout_s:.1f}s "
                                f"exceeded while queued ({waited:.1f}s)")
                        # the deadline rides the cooperative-cancel
                        # machinery: when it fires mid-execution the next
                        # raise_if_cancelled checkpoint aborts the query

                        def _deadline():
                            with self._reg_lock:
                                fire = ss.running_stmt == stmt.id
                            if fire:
                                stmt.cancel_requested = True
                                ss.session.cancelAllQueries()

                        timer = threading.Timer(timeout_s - waited,
                                                _deadline)
                        timer.daemon = True
                        timer.start()
                    ss.last_used = time.time()
                    t0 = time.time()
                    ss.session._last_plan_cache_info = None
                    df = ss.session.sql(stmt.query)
                    columns = list(df.schema.names)
                    rows = [[_json_safe(v) for v in r]
                            for r in df.collect()]
                    info = getattr(ss.session,
                                   "_last_plan_cache_info", None) or {}
                    return {"columns": columns, "rows": rows,
                            "rowCount": len(rows),
                            "durationMs":
                                round((time.time() - t0) * 1000, 1),
                            "statementId": stmt.id,
                            "cacheHit": bool(info.get("hit")),
                            "planningSkippedMs":
                                round(float(info.get("skippedMs", 0.0)), 1)}
                finally:
                    if timer is not None:
                        timer.cancel()
                    with self._reg_lock:
                        if ss.running_stmt == stmt.id:
                            ss.running_stmt = None

        # one pool slot per BUSY SESSION, not per statement: the work unit
        # joins the session's FIFO, and a drainer task is spawned only if
        # none is already running this session's queue.  The HTTP handler
        # thread (not a pool thread) blocks on the future, so a session
        # with a deep backlog cannot exhaust the worker pool.
        future: Future = Future()
        with self._reg_lock:
            ss.queue.append((stmt, future, work))
            spawn = not ss.draining
            if spawn:
                ss.draining = True
        if spawn:
            self._pool.submit(self._drain_session, ss)
        try:
            out = future.result()
            stmt.status = "done"
            return out
        except QueryCancelled:
            stmt.status = "cancelled"
            raise
        except Exception:
            if stmt.status != "cancelled":
                stmt.status = "error"
            raise

    def _drain_session(self, ss: _ServerSession) -> None:
        """Run one session's queued statements serially on this single
        worker slot; exits (clearing ``draining``) when the FIFO empties,
        holding ``_reg_lock`` for the check so no enqueue slips between
        'queue is empty' and 'drainer gone'."""
        while True:
            with self._reg_lock:
                if not ss.queue:
                    ss.draining = False
                    return
                _stmt, future, work = ss.queue.popleft()
            if not future.set_running_or_notify_cancel():
                continue
            try:
                future.set_result(work())
            except BaseException as e:  # noqa: BLE001 — deliver to waiter
                future.set_exception(e)

    _MAX_FINISHED_STATEMENTS = 1000

    def _evict_statements(self) -> None:
        """Cap the registry: drop oldest TERMINAL statements beyond the
        bound (caller holds _reg_lock) — a serving process must not leak
        one entry per request."""
        done = [s for s in self._statements.values()
                if s.status not in ("queued", "running")]
        excess = len(done) - self._MAX_FINISHED_STATEMENTS
        if excess > 0:
            for s in sorted(done, key=lambda s: s.submitted)[:excess]:
                self._statements.pop(s.id, None)

    def _cancel(self, stmt_id: str) -> dict:
        from .sql.session import QueryCancelled

        stmt = self._statements.get(stmt_id)
        if stmt is None:
            raise KeyError(f"no such statement {stmt_id!r}")
        stmt.cancel_requested = True
        try:
            ss: Optional[_ServerSession] = \
                self._resolve(stmt.session_id or None)
        except KeyError:      # session already closed; flag alone suffices
            ss = None
        removed = None
        fire = False
        if ss is not None:
            with self._reg_lock:
                # a QUEUED statement is cancelled synchronously: pulled
                # out of the FIFO here, its waiter resolved below — no
                # worker slot is ever spent on it
                for item in ss.queue:
                    if item[0] is stmt:
                        removed = item
                        break
                if removed is not None:
                    ss.queue.remove(removed)
                else:
                    # only interrupt the session if OUR statement is the
                    # one on it right now — between reading status and
                    # firing the cancel the target may have finished and
                    # a DIFFERENT statement started, and interrupting
                    # that innocent one would be the
                    # cancel-the-wrong-statement race
                    fire = ss.running_stmt == stmt_id
        if removed is not None:
            stmt.status = "cancelled"
            removed[1].set_exception(
                QueryCancelled("cancelled while queued"))
        elif fire:
            ss.session.cancelAllQueries()
        return {"statementId": stmt_id, "status": stmt.status,
                "cancelRequested": True}

    def _status(self) -> dict:
        with self._reg_lock:
            stmts = {s.id: s.status for s in self._statements.values()
                     if s.status in ("queued", "running")}
            n_sessions = len(self._sessions)
            queues = {sid: {"queued": len(ss.queue),
                            "running": ss.running_stmt is not None}
                      for sid, ss in self._sessions.items()}
            streams = {stream_id: {"session": sid, "active": q.isActive}
                       for sid, ss in [("default", self._default),
                                       *self._sessions.items()]
                       for stream_id, q in ss.streams.items()}
            grace = {sid: g for sid, ss in self._sessions.items()
                     if (g := self._grace_stats(ss.session))}
            ici = {sid: g for sid, ss in self._sessions.items()
                   if (g := self._ici_stats(ss.session))}
            runact = {sid: g for sid, ss in self._sessions.items()
                      if (g := self._run_stats(ss.session))}
        default_grace = self._grace_stats(self.session)
        if default_grace:
            grace["default"] = default_grace
        default_ici = self._ici_stats(self.session)
        if default_ici:
            ici["default"] = default_ici
        default_run = self._run_stats(self.session)
        if default_run:
            runact["default"] = default_run
        out = {
            "version": self.session.version,
            "queriesExecuted": getattr(self.session, "_query_count", 0),
            "sessions": n_sessions,
            "sessionsExpired": self._sessions_expired,
            "activeStatements": stmts,
            "sessionQueues": queues,
            "standingQueries": streams,
            "admission": self._admission.stats(),
            "graceActivity": grace,
            "iciActivity": ici,
            "runActivity": runact,
            "metrics": self.session.metricsSystem.snapshots(),
        }
        if self._plan_cache is not None:
            out["planCache"] = self._plan_cache.stats()
        if self._blockserver is not None:
            out["blockStore"] = self._blockserver.stats()
        if self._pool_supervisor is not None:
            out["poolActivity"] = self._pool_supervisor.stats()
        from .sql.stagecompile import stage_cache
        out["stageCache"] = stage_cache().stats()
        return out

    # -- http plumbing ---------------------------------------------------
    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):      # quiet by default
                pass

            def _reply(self, code: int, payload: dict,
                       headers: Optional[Dict[str, str]] = None):
                body = json.dumps(payload, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _authed(self) -> bool:
                if server.token is None:
                    return True
                got = self.headers.get("Authorization", "")
                want = f"Bearer {server.token}"
                # constant-time compare: a == on secrets leaks a timing
                # oracle over the token prefix to anyone who can POST
                if hmac.compare_digest(got.encode(), want.encode()):
                    return True
                self._reply(401, {"error": "missing or bad bearer token"})
                return False

            def do_GET(self):
                if not self._authed():
                    return
                path = self.path.rstrip("/")
                if path in ("", "/status"):
                    self._reply(200, server._status())
                elif path.startswith("/statement/"):
                    stmt = server._statements.get(path.rsplit("/", 1)[1])
                    if stmt is None:
                        self._reply(404, {"error": "no such statement"})
                    else:
                        self._reply(200, {
                            "statementId": stmt.id, "status": stmt.status,
                            "submitted": stmt.submitted})
                elif path.startswith("/stream/"):
                    try:
                        self._reply(200, server._stream_status(
                            path.rsplit("/", 1)[1]))
                    except KeyError as e:
                        self._reply(404, {"error": str(e)})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_DELETE(self):
                if not self._authed():
                    return
                path = self.path.rstrip("/")
                if path.startswith("/session/"):
                    sid = path.rsplit("/", 1)[1]
                    if server._close_session(sid):
                        self._reply(200, {"closed": sid})
                    else:
                        self._reply(404, {"error": f"no session {sid!r}"})
                elif path.startswith("/stream/"):
                    try:
                        self._reply(200, server._stop_stream(
                            path.rsplit("/", 1)[1]))
                    except KeyError as e:
                        self._reply(404, {"error": str(e)})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if not self._authed():
                    return
                path = self.path.rstrip("/")
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n).decode("utf-8", "replace")
                payload: Dict[str, Any] = {}
                if raw.lstrip().startswith("{"):
                    try:
                        payload = json.loads(raw)
                    except json.JSONDecodeError:
                        payload = {}
                if path == "/session":
                    try:
                        self._reply(200, {"sessionId": server._open_session()})
                    except RuntimeError as e:
                        self._reply(429, {"error": str(e)})
                    return
                if path == "/stream":
                    try:
                        self._reply(200, server._start_stream(payload))
                    except AdmissionRejected as e:
                        self._reply(429, e.to_json(), headers={
                            "Retry-After": str(max(1, int(
                                e.retry_after_s + 0.999)))})
                    except KeyError as e:
                        self._reply(404, {"error": str(e)})
                    except Exception as e:  # noqa: BLE001 — to client
                        self._reply(400, {
                            "error": f"{type(e).__name__}: {e}"[:2000]})
                    return
                if path == "/cancel":
                    sid = payload.get("id") or \
                        self.headers.get("X-Statement-Id")
                    try:
                        self._reply(200, server._cancel(sid or ""))
                    except KeyError as e:
                        self._reply(404, {"error": str(e)})
                    return
                if path != "/sql":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                text = payload.get("query", "") if payload else raw
                sid = (payload.get("session")
                       or self.headers.get("X-Session-Id"))
                stmt_id = (payload.get("id")
                           or self.headers.get("X-Statement-Id"))
                if not isinstance(text, str) or not text.strip():
                    self._reply(400, {"error": "empty or non-string query"})
                    return
                from .sql.session import QueryCancelled
                try:
                    self._reply(200, server._run_sql(text, sid, stmt_id))
                except AdmissionRejected as e:
                    self._reply(429, e.to_json(), headers={
                        "Retry-After": str(max(1, int(e.retry_after_s
                                                      + 0.999)))})
                except QueryCancelled as e:
                    self._reply(499, {"error": f"cancelled: {e}",
                                      "statementId": stmt_id})
                except KeyError as e:
                    self._reply(404, {"error": str(e)})
                except Exception as e:    # noqa: BLE001 — surface to client
                    self._reply(400, {
                        "error": f"{type(e).__name__}: {e}"[:2000]})

        return Handler

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "SQLServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]     # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"sql-server-{self.port}")
        self._thread.start()
        self._reaper_stop.clear()
        self._reaper = threading.Thread(
            target=self._reap_loop, daemon=True,
            name=f"sql-server-reaper-{self.port}")
        self._reaper.start()
        bc = getattr(getattr(self.session, "_crossproc_svc", None),
                     "blockclient", None)
        if bc is not None and self._blockserver is None:
            from .parallel.blockserver import BlockServer
            self._blockserver = BlockServer(
                bc.store, roots=(bc.store.root,),
                interval_s=float(self.session.conf_obj.get(
                    C.BLOCKSERVER_GC_INTERVAL)))
            self._blockserver.start()
        if self.session.conf_obj.get(C.SERVER_POOL_ENABLED) \
                and self._pool_supervisor is None:
            from .serving.pool import WorkerPoolSupervisor
            svc = getattr(self.session, "_crossproc_svc", None)
            pool_root = os.path.join(
                getattr(svc, "root", None)
                or os.path.abspath(self.session.conf_obj.get(
                    C.WAREHOUSE_DIR)) + "-ctl",
                "_pool")
            self._pool_supervisor = WorkerPoolSupervisor(
                pool_root, self.session.conf_obj,
                demand_supplier=self._admission.demand_signal,
                warehouse=os.path.abspath(
                    self.session.conf_obj.get(C.WAREHOUSE_DIR)),
                blockstore_root=(bc.store.root if bc is not None
                                 else None))
            self._pool_supervisor.start()
        return self

    def stop(self) -> None:
        self._reaper_stop.set()
        if self._reaper is not None:
            self._reaper.join(timeout=2.0)
            self._reaper = None
        if self._pool_supervisor is not None:
            self._pool_supervisor.stop()
            self._pool_supervisor = None
        if self._blockserver is not None:
            self._blockserver.stop()
            self._blockserver = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        self._pool.shutdown(wait=False, cancel_futures=True)
        with self._reg_lock:
            sessions = list(self._sessions.values())
        for ss in [self._default] + sessions:
            self._release_session_streams(ss)
        for ss in sessions:
            ss.session._plan_cache = None
        self.session._plan_cache = None
        ms = self.session.metricsSystem
        ms._sources = [s for s in ms._sources
                       if s.name not in ("serving", "pool")]


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8123)
    ap.add_argument("--workers", type=int, default=4,
                    help="bounded statement worker pool size")
    ap.add_argument("--token", default=None,
                    help="shared-secret bearer token (or "
                    "SPARK_TPU_SERVER_TOKEN)")
    args = ap.parse_args(argv)

    from .sql.session import SparkSession
    session = SparkSession.builder.appName("sql-server").getOrCreate()
    srv = SQLServer(session, args.host, args.port, workers=args.workers,
                    token=args.token).start()
    auth = "token-protected" if srv.token else "no auth"
    print(f"spark_tpu SQL server on http://{srv.host}:{srv.port} "
          f"({args.workers} workers, {auth}; POST /sql, /session, "
          f"/cancel; GET /status)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
