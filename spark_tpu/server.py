"""SQL-over-HTTP serving endpoint.

The serving role of the reference's `sql/hive-thriftserver` (71.7k LoC
of HiveServer2 protocol) re-based on the one wire format every client
already speaks: POST a SQL string, receive JSON rows.  Sessions execute
serially under a lock (the engine's jit/plan caches are per-session
state, exactly like a Thrift session handle); the server is a thin
stateless shell over one SparkSession, matching the
"filesystem-catalog + CLI" Hive divergence recorded in
docs/DECISIONS.md.

    python -m spark_tpu.server --port 8123 &
    curl -d 'SELECT 1 AS x' localhost:8123/sql

Endpoints:
    POST /sql      body = SQL text (or JSON {"query": ...}) → JSON
                   {"columns", "rows", "rowCount", "durationMs"}
    GET  /status   engine version, query counter, metrics snapshot
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

__all__ = ["SQLServer"]


def _json_safe(v: Any):
    if isinstance(v, float):
        # RFC 8259 has no NaN/Infinity literals; strict clients reject them
        if v != v:
            return None
        if v in (float("inf"), float("-inf")):
            return str(v)
        return v
    if v is None or isinstance(v, (bool, int, str)):
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    return str(v)


class SQLServer:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 8123):
        self.session = session
        self.host = host
        self.port = port
        self._lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # -- request handling ------------------------------------------------
    def _run_sql(self, text: str) -> dict:
        t0 = time.time()
        with self._lock:                 # session state is single-writer
            df = self.session.sql(text)
            columns = list(df.schema.names)
            rows = [[_json_safe(v) for v in r] for r in df.collect()]
        return {"columns": columns, "rows": rows, "rowCount": len(rows),
                "durationMs": round((time.time() - t0) * 1000, 1)}

    def _status(self) -> dict:
        return {
            "version": self.session.version,
            "queriesExecuted": getattr(self.session, "_query_count", 0),
            "metrics": self.session.metricsSystem.snapshots(),
        }

    def _make_handler(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):      # quiet by default
                pass

            def _reply(self, code: int, payload: dict):
                body = json.dumps(payload, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.rstrip("/") in ("", "/status"):
                    self._reply(200, server._status())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path.rstrip("/") != "/sql":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(n).decode("utf-8", "replace")
                text = raw
                if raw.lstrip().startswith("{"):
                    try:
                        text = json.loads(raw).get("query", "")
                    except json.JSONDecodeError:
                        pass
                if not text.strip():
                    self._reply(400, {"error": "empty query"})
                    return
                try:
                    self._reply(200, server._run_sql(text))
                except Exception as e:    # noqa: BLE001 — surface to client
                    self._reply(400, {
                        "error": f"{type(e).__name__}: {e}"[:2000]})

        return Handler

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "SQLServer":
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          self._make_handler())
        self.port = self._httpd.server_address[1]     # resolve port 0
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"sql-server-{self.port}")
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8123)
    args = ap.parse_args(argv)

    from .sql.session import SparkSession
    session = SparkSession.builder.appName("sql-server").getOrCreate()
    srv = SQLServer(session, args.host, args.port).start()
    print(f"spark_tpu SQL server on http://{srv.host}:{srv.port} "
          f"(POST /sql, GET /status)")
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
