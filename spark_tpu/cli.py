"""Command-line entry points (the `bin/` + `launcher/` analog).

The reference ships shell scripts that assemble a JVM command line
(`bin/spark-submit` -> `launcher/Main.java` -> `SparkSubmit.scala:109`);
here the driver IS Python, so the launcher collapses to argv dispatch:

    python -m spark_tpu.cli submit app.py [args...]   # spark-submit
    python -m spark_tpu.cli sql [-e QUERY] [-f FILE]  # spark-sql shell
    python -m spark_tpu.cli shell                     # pyspark-style REPL

Repo-root `bin/` holds one-line shims for each.
"""

from __future__ import annotations

import argparse
import code
import runpy
import sys
from typing import List, Optional


def _parse_conf_pair(pair: str):
    if "=" not in pair:
        raise SystemExit(f"--conf expects key=value, got {pair!r}")
    return pair.split("=", 1)


def _run_script(script: str, script_args) -> int:
    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")
    return 0


def _session(conf_pairs: List[str]):
    from spark_tpu.sql.session import SparkSession
    # --conf must flow through the BUILDER: SparkSession.__init__ reads
    # config (HBM budget, storage fraction) during construction
    b = SparkSession.builder.appName("spark-tpu-cli")
    for pair in conf_pairs or []:
        k, v = _parse_conf_pair(pair)
        b = b.config(k, v)
    return b.getOrCreate()


def split_sql_statements(text: str) -> List[str]:
    """Split a script on ';' outside quotes (single, double, and '--'
    line comments), so literals like SELECT ';' survive."""
    out: List[str] = []
    buf: List[str] = []
    quote: Optional[str] = None
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if quote:
            buf.append(ch)
            if ch == quote:
                # doubled quote inside a literal is an escape ('' / "")
                if i + 1 < n and text[i + 1] == quote:
                    buf.append(text[i + 1])
                    i += 1
                else:
                    quote = None
        elif ch in ("'", '"'):
            quote = ch
            buf.append(ch)
        elif ch == "-" and i + 1 < n and text[i + 1] == "-":
            while i < n and text[i] != "\n":
                i += 1
            continue
        elif ch == ";":
            out.append("".join(buf).strip())
            buf = []
        else:
            buf.append(ch)
        i += 1
    tail = "".join(buf).strip()
    if tail:
        out.append(tail)
    return [s for s in out if s]


def statements_if_complete(text: str) -> Optional[List[str]]:
    """Statements of ``text`` if it ends with a ';' OUTSIDE any string
    literal/comment; None while a literal is open or no terminator yet."""
    quote: Optional[str] = None
    ends_semi = False
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if quote:
            if ch == quote:
                if i + 1 < n and text[i + 1] == quote:
                    i += 1
                else:
                    quote = None
        elif ch in ("'", '"'):
            quote = ch
            ends_semi = False     # a literal after ';' starts a new stmt
        elif ch == "-" and i + 1 < n and text[i + 1] == "-":
            while i < n and text[i] != "\n":
                i += 1
            continue
        elif ch == ";":
            ends_semi = True
        elif not ch.isspace():
            ends_semi = False
        i += 1
    if quote is not None or not ends_semi:
        return None
    return split_sql_statements(text)


def _show(df) -> None:
    df.show(100)


def cmd_submit(args) -> int:
    """Run a user script with sys.argv rewritten (SparkSubmit.runMain:
    the script builds its own session via SparkSession.builder)."""
    _session(args.conf)     # pre-warm the active session with --conf
    return _run_script(args.script, args.script_args)


def cmd_launch(args) -> int:
    """Multi-process launcher (the SparkSubmit → Master/Worker role,
    `deploy/SparkSubmit.scala:66` + `master/Master.scala:41`, collapsed
    onto jax.distributed: no Master daemon — a coordinator address and a
    process index are the entire control plane; docs/DEPLOY.md).

    Modes:
    * fan-out (no --process-id): spawn --processes local workers, each
      re-entering this command with its own index — the local-cluster
      dev mode;
    * worker (--process-id given): export the cluster coordinates via
      SPARK_TPU_* env and run the script, which joins by calling
      ``init_cluster()`` with no arguments.  On a multi-host deployment
      the operator (or the GKE JobSet) runs THIS mode once per host."""
    import os
    import socket

    if args.process_id is None:
        # negatives clamp to 0 (no infinite-restart mode: a crash-looping
        # gang burns the TPU reservation; supervise with a real orchestrator
        # if unbounded restarts are wanted)
        max_restarts = max(0, getattr(args, "max_restarts", 0) or 0)
        for attempt in range(max_restarts + 1):
            coord = args.coordinator
            if coord is None:
                # ephemeral-port probe: closed before process 0's
                # coordinator rebinds it — a small TOCTOU window another
                # process could steal the port in (kernels rarely
                # reassign a just-released ephemeral port, and jax's
                # coordinator sets SO_REUSEADDR); pass --coordinator
                # explicitly on busy shared hosts.  Re-probed per attempt:
                # a crashed gang can leave the old port in TIME_WAIT.
                with socket.socket() as s:
                    s.bind(("localhost", 0))
                    coord = f"localhost:{s.getsockname()[1]}"
            cmds = []
            for i in range(args.processes):
                argv = [sys.executable, "-m", "spark_tpu.cli",
                        "launch", "--coordinator", coord,
                        "--processes", str(args.processes),
                        "--process-id", str(i)]
                for c in args.conf:
                    argv += ["--conf", c]
                argv += [args.script] + list(args.script_args)
                cmds.append(argv)
            # all-or-none through the pool's spawn seam: on a partial
            # spawn the already-started workers are terminated AND
            # waited (previously they were only sent SIGTERM and could
            # linger at the rendezvous for jax's whole init timeout)
            from .serving.pool import spawn_gang
            procs = spawn_gang(cmds)
            # any worker failing (incl. SIGNAL deaths, which report
            # negative) fails the attempt and kills the siblings —
            # otherwise survivors spin at the jax.distributed rendezvous
            # for its full timeout.  The REPORTED code is the FIRST
            # failure's (the cause), not the SIGTERM this launcher then
            # sends to the others.
            first_rc = 0
            pending = set(procs)
            while pending:
                for pr in list(pending):
                    status = pr.poll()
                    if status is None:
                        continue
                    pending.discard(pr)
                    if status != 0 and first_rc == 0:
                        first_rc = 128 + abs(status) if status < 0 \
                            else status
                        for other in pending:
                            other.terminate()
                if pending:
                    import time as _t
                    _t.sleep(0.1)
            if first_rc == 0:
                return 0
            if attempt < max_restarts:
                # WHOLE-gang restart (collectives cannot survive a lost
                # member): checkpointed queries resume from their WAL /
                # multibatch checkpoints — `spark-submit --supervise`
                # (deploy/Client.scala) semantics at gang granularity
                print(f"[spark-tpu-launch] gang failed (rc={first_rc}); "
                      f"restart {attempt + 1}/{max_restarts}",
                      file=sys.stderr)
        return first_rc

    env_coord = args.coordinator
    if env_coord is not None:
        os.environ["SPARK_TPU_COORDINATOR"] = env_coord
    if args.processes:
        os.environ["SPARK_TPU_NUM_PROCESSES"] = str(args.processes)
    os.environ["SPARK_TPU_PROCESS_ID"] = str(args.process_id)
    # UNLIKE cmd_submit, no session pre-warm here: touching the XLA
    # backend before the script's init_cluster() would make
    # jax.distributed.initialize impossible.  --conf pairs ride the
    # environment and apply when the script builds its session.
    if args.conf:
        pairs = ["=".join(_parse_conf_pair(p)) for p in args.conf]
        os.environ["SPARK_TPU_LAUNCH_CONF"] = "\x1f".join(pairs)
    return _run_script(args.script, args.script_args)


def cmd_sql(args) -> int:
    """spark-sql: execute -e/-f statements or run an interactive loop
    (`SparkSQLCLIDriver` analog)."""
    spark = _session(args.conf)
    if args.e:
        _show(spark.sql(args.e))
        return 0
    if args.f:
        with open(args.f) as fh:
            text = fh.read()
        for stmt in split_sql_statements(text):
            _show(spark.sql(stmt))
        return 0
    print("spark-tpu-sql interactive shell; end statements with ';', "
          "exit with 'quit;'")
    buf: List[str] = []
    while True:
        try:
            line = input("spark-sql> " if not buf else "         > ")
        except EOFError:
            break
        buf.append(line)
        joined = "\n".join(buf)
        stmts = statements_if_complete(joined)
        if stmts is None:          # open literal or no terminating ';'
            continue
        buf = []
        for stmt in stmts:
            if stmt.lower() in ("quit", "exit"):
                return 0
            try:
                _show(spark.sql(stmt))
            except Exception as e:        # noqa: BLE001 — REPL keeps going
                print(f"Error: {e}", file=sys.stderr)
    return 0


def cmd_shell(args) -> int:
    """pyspark-style Python REPL with `spark` and `sc` bound."""
    spark = _session(args.conf)
    banner = ("spark_tpu shell\n"
              "SparkSession available as 'spark', "
              "SparkContext as 'sc'.")
    ns = {"spark": spark, "sc": spark.sparkContext}
    code.interact(banner=banner, local=ns)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="spark_tpu.cli")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("submit", help="run a python app (spark-submit)")
    ps.add_argument("--conf", action="append", default=[])
    ps.add_argument("script")
    ps.add_argument("script_args", nargs=argparse.REMAINDER)
    ps.set_defaults(fn=cmd_submit)

    pl = sub.add_parser(
        "launch", help="multi-process launcher (spark-submit --deploy)")
    pl.add_argument("--processes", type=int, default=1,
                    help="total processes in the cluster")
    pl.add_argument("--coordinator", default=None,
                    help="host:port of process 0 (auto for local fan-out)")
    pl.add_argument("--process-id", type=int, default=None,
                    help="this process's index; omit to fan out locally")
    pl.add_argument("--max-restarts", type=int, default=0,
                    help="supervise: relaunch the WHOLE gang up to N "
                         "times after a failure (checkpointed queries "
                         "resume); the spark-submit --supervise role")
    pl.add_argument("--conf", action="append", default=[])
    pl.add_argument("script")
    pl.add_argument("script_args", nargs=argparse.REMAINDER)
    pl.set_defaults(fn=cmd_launch)

    pq = sub.add_parser("sql", help="SQL shell (spark-sql)")
    pq.add_argument("-e", help="execute one statement and exit")
    pq.add_argument("-f", help="execute statements from a file")
    pq.add_argument("--conf", action="append", default=[])
    pq.set_defaults(fn=cmd_sql)

    pr = sub.add_parser("shell", help="python REPL with a session (pyspark)")
    pr.add_argument("--conf", action="append", default=[])
    pr.set_defaults(fn=cmd_shell)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
