"""Command-line entry points (the `bin/` + `launcher/` analog).

The reference ships shell scripts that assemble a JVM command line
(`bin/spark-submit` -> `launcher/Main.java` -> `SparkSubmit.scala:109`);
here the driver IS Python, so the launcher collapses to argv dispatch:

    python -m spark_tpu.cli submit app.py [args...]   # spark-submit
    python -m spark_tpu.cli sql [-e QUERY] [-f FILE]  # spark-sql shell
    python -m spark_tpu.cli shell                     # pyspark-style REPL

Repo-root `bin/` holds one-line shims for each.
"""

from __future__ import annotations

import argparse
import code
import runpy
import sys
from typing import List, Optional


def _session(conf_pairs: List[str]):
    from spark_tpu.sql.session import SparkSession
    b = SparkSession.builder.appName("spark-tpu-cli")
    s = b.getOrCreate()
    for pair in conf_pairs or []:
        if "=" not in pair:
            raise SystemExit(f"--conf expects key=value, got {pair!r}")
        k, v = pair.split("=", 1)
        s.conf.set(k, v)
    return s


def _show(df) -> None:
    df.show(100)


def cmd_submit(args) -> int:
    """Run a user script with sys.argv rewritten (SparkSubmit.runMain:
    the script builds its own session via SparkSession.builder)."""
    _session(args.conf)     # pre-warm the active session with --conf
    sys.argv = [args.script] + list(args.script_args)
    runpy.run_path(args.script, run_name="__main__")
    return 0


def cmd_sql(args) -> int:
    """spark-sql: execute -e/-f statements or run an interactive loop
    (`SparkSQLCLIDriver` analog)."""
    spark = _session(args.conf)
    if args.e:
        _show(spark.sql(args.e))
        return 0
    if args.f:
        with open(args.f) as fh:
            text = fh.read()
        for stmt in [s.strip() for s in text.split(";") if s.strip()]:
            _show(spark.sql(stmt))
        return 0
    print("spark-tpu-sql interactive shell; end statements with ';', "
          "exit with 'quit;'")
    buf: List[str] = []
    while True:
        try:
            line = input("spark-sql> " if not buf else "         > ")
        except EOFError:
            break
        buf.append(line)
        joined = "\n".join(buf)
        if joined.rstrip().endswith(";"):
            stmt = joined.rstrip()[:-1].strip()
            buf = []
            if stmt.lower() in ("quit", "exit"):
                break
            if not stmt:
                continue
            try:
                _show(spark.sql(stmt))
            except Exception as e:        # noqa: BLE001 — REPL keeps going
                print(f"Error: {e}", file=sys.stderr)
    return 0


def cmd_shell(args) -> int:
    """pyspark-style Python REPL with `spark` and `sc` bound."""
    spark = _session(args.conf)
    banner = ("spark_tpu shell\n"
              "SparkSession available as 'spark', "
              "SparkContext as 'sc'.")
    ns = {"spark": spark, "sc": spark.sparkContext}
    code.interact(banner=banner, local=ns)
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="spark_tpu.cli")
    sub = p.add_subparsers(dest="cmd", required=True)

    ps = sub.add_parser("submit", help="run a python app (spark-submit)")
    ps.add_argument("--conf", action="append", default=[])
    ps.add_argument("script")
    ps.add_argument("script_args", nargs=argparse.REMAINDER)
    ps.set_defaults(fn=cmd_submit)

    pq = sub.add_parser("sql", help="SQL shell (spark-sql)")
    pq.add_argument("-e", help="execute one statement and exit")
    pq.add_argument("-f", help="execute statements from a file")
    pq.add_argument("--conf", action="append", default=[])
    pq.set_defaults(fn=cmd_sql)

    pr = sub.add_parser("shell", help="python REPL with a session (pyspark)")
    pr.add_argument("--conf", action="append", default=[])
    pr.set_defaults(fn=cmd_shell)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
