"""Columnar batches: the device-native data representation.

This replaces the reference's row format stack — ``UnsafeRow.java:62``,
``ColumnarBatch.java:46`` / ``ColumnVector.java:60`` — with a
structure-of-arrays layout designed for XLA:

* every column is ONE flat device array of a fixed-width dtype, padded to a
  static ``capacity`` (power of two) so shapes never depend on data;
* row existence (``row_valid``) and per-column NULLs (``ColumnVector.valid``)
  are separate boolean masks (Arrow-style validity);
* strings/binary are dictionary codes (``int32``) into a host-side,
  lexicographically sorted dictionary, so all device ops on strings are
  integer ops (see ``types.StringType``);
* a ``ColumnBatch`` is a registered JAX pytree, so whole operator pipelines
  over batches trace into a single XLA program (the WholeStageCodegen analog).

Filtering does NOT compact (it just ANDs ``row_valid``); ``compact`` is an
explicit operator applied only where order/size matters (exchange, limit).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp

from . import types as T

Array = Any  # np.ndarray | jax.Array

MIN_CAPACITY = 8


def pad_capacity(n: int) -> int:
    """Round row count up to the static batch capacity (next power of two)."""
    c = MIN_CAPACITY
    while c < n:
        c <<= 1
    return c


def _xp(arr: Array):
    return jnp if isinstance(arr, jax.Array) else np


def pad_to_capacity(batch: "ColumnBatch", cap: int) -> "ColumnBatch":
    """Grow a HOST batch to a larger static capacity.

    Streamed scans pad every batch to ONE shared capacity so the per-batch
    jitted step compiles once (the multi-batch analog of the reference's
    fixed ColumnarBatch capacity, `ColumnarBatch.java:46`)."""
    if cap < batch.capacity:
        raise ValueError(f"cannot shrink batch {batch.capacity} -> {cap}")
    if cap == batch.capacity:
        return batch
    pad = cap - batch.capacity
    vectors = []
    for v in batch.vectors:
        data = np.concatenate(
            [np.asarray(v.data), np.zeros(pad, np.asarray(v.data).dtype)])
        valid = None
        if v.valid is not None:
            valid = np.concatenate(
                [np.asarray(v.valid), np.zeros(pad, bool)])
        vectors.append(ColumnVector(data, v.dtype, valid, v.dictionary))
    rv = np.zeros(cap, bool)
    rv[:batch.capacity] = np.asarray(batch.row_valid_or_true())
    return ColumnBatch(list(batch.names), vectors, rv, cap)


def normalize_valids(batch: "ColumnBatch") -> "ColumnBatch":
    """Materialize every implicit (None) validity mask as an explicit array.

    Validity masks live in the pytree STRUCTURE (None vs array), so two scan
    batches that differ only in "column happened to contain a null" would
    retrace the jitted per-batch step; normalizing makes the treedef stable
    across a streamed scan."""
    vectors = [
        v if v.valid is not None else
        ColumnVector(v.data, v.dtype,
                     np.ones(batch.capacity, bool), v.dictionary)
        for v in batch.vectors
    ]
    rv = batch.row_valid
    if rv is None:
        rv = np.ones(batch.capacity, bool)
    return ColumnBatch(list(batch.names), vectors, rv, batch.capacity)


#: running total of dictionary codes decoded back into Python words —
#: the "late materialization" boundary.  Codes that stay codes through
#: exchange/join/group never show up here; only collect()-style output
#: does.  Plain module int: metrics-grade accuracy is enough.
_LATE_MATERIALIZED_ROWS = 0


def late_materialized_rows() -> int:
    """Total dictionary-encoded values decoded to Python objects so far
    (process-wide; gauge consumers diff against a baseline)."""
    return _LATE_MATERIALIZED_ROWS


#: running total of run-encoded values expanded to dense arrays — the run
#: analog of ``_LATE_MATERIALIZED_ROWS``.  Columns that stay run-encoded
#: through filter/aggregate/join never show up here; only operators that
#: genuinely need the dense form (or ``to_pylist``) do.
_RUNS_MATERIALIZED = 0

#: running total of rows whose operator work was done at run granularity
#: (one predicate eval / one probe / one multiply per run instead of per
#: row) — proof the run-aware fast paths actually fired.
_RUN_AWARE_OP_ROWS = 0


def runs_materialized() -> int:
    """Total run-encoded values expanded to dense arrays so far
    (process-wide; gauge consumers diff against a baseline)."""
    return _RUNS_MATERIALIZED


def run_aware_op_rows() -> int:
    """Total rows served by run-aware operator fast paths so far
    (process-wide; gauge consumers diff against a baseline)."""
    return _RUN_AWARE_OP_ROWS


def bump_run_aware(n: int) -> None:
    """Credit ``n`` rows to the run-aware fast-path counter."""
    global _RUN_AWARE_OP_ROWS
    _RUN_AWARE_OP_ROWS += int(n)


#: run-plane activity — the device-side close of the run line.  A "plane"
#: is the fixed-capacity pytree form of a run table (see
#: ``PlaneColumnVector``): stages count stage entries that carried at
#: least one plane input, rows count the dense rows those planes stood in
#: for, overflows count run tables too large to compress (fell back to
#: counted materialization at the boundary), and expansions count
#: in-TRACE dense expansions (an untaught operator read ``.data`` inside
#: a jitted stage — paid in device gathers, never host inflation, and
#: counted once per trace, not per dispatch).
_RUN_PLANE_STAGES = 0
_RUN_PLANE_ROWS = 0
_RUN_PLANE_OVERFLOWS = 0
_RUN_PLANE_EXPANSIONS = 0


def run_plane_stages() -> int:
    """Stage dispatches that carried at least one run-plane input
    (process-wide; gauge consumers diff against a baseline)."""
    return _RUN_PLANE_STAGES


def run_plane_rows() -> int:
    """Dense rows that crossed the jit boundary as run planes instead of
    materialized arrays (process-wide)."""
    return _RUN_PLANE_ROWS


def run_plane_overflows() -> int:
    """Run vectors whose run count was too large for a compressing plane
    — materialized counted at the boundary instead (process-wide)."""
    return _RUN_PLANE_OVERFLOWS


def run_plane_expansions() -> int:
    """In-trace searchsorted-gather expansions of a plane by an untaught
    operator — once per trace, not per dispatch (process-wide)."""
    return _RUN_PLANE_EXPANSIONS


def bump_plane_stage() -> None:
    global _RUN_PLANE_STAGES
    _RUN_PLANE_STAGES += 1


def bump_plane_rows(n: int) -> None:
    global _RUN_PLANE_ROWS
    _RUN_PLANE_ROWS += int(n)


def bump_plane_overflow() -> None:
    global _RUN_PLANE_OVERFLOWS
    _RUN_PLANE_OVERFLOWS += 1


def encode_strings(values: Sequence[Optional[str]]) -> Tuple[np.ndarray, Tuple[str, ...]]:
    """Dictionary-encode strings: codes into a SORTED dictionary.

    Sorted dictionaries make code order == lexicographic order, so device
    sorts/compares on codes are string-correct (the UTF8String replacement).
    Returns (int32 codes with -1 for None, dictionary tuple).
    """
    present = sorted({v for v in values if v is not None})
    lookup = {v: i for i, v in enumerate(present)}
    codes = np.fromiter(
        (lookup[v] if v is not None else -1 for v in values),
        dtype=np.int32, count=len(values),
    )
    return codes, tuple(present)


def merge_dictionaries(
    a: Tuple[str, ...], b: Tuple[str, ...]
) -> Tuple[Tuple[str, ...], np.ndarray, np.ndarray]:
    """Merge two sorted dictionaries; return (merged, remap_a, remap_b).

    ``remap_x[old_code] -> new_code``. Needed when two independently encoded
    string columns meet (union, join keys, comparisons).
    """
    merged = tuple(sorted(set(a) | set(b)))
    lookup = {v: i for i, v in enumerate(merged)}
    remap_a = np.fromiter((lookup[v] for v in a), dtype=np.int32, count=len(a))
    remap_b = np.fromiter((lookup[v] for v in b), dtype=np.int32, count=len(b))
    return merged, remap_a, remap_b


class ColumnVector:
    """One column: data array + optional validity mask (+ string dictionary).

    ``valid is None`` means "no NULLs". The dictionary is host metadata
    (static under jit); data/valid may be numpy (host) or jax.Array (device).
    """

    __slots__ = ("data", "valid", "dtype", "dictionary")

    def __init__(self, data: Array, dtype: T.DataType,
                 valid: Optional[Array] = None,
                 dictionary: Optional[Tuple[str, ...]] = None):
        self.data = data
        self.dtype = dtype
        self.valid = valid
        self.dictionary = dictionary

    def __repr__(self) -> str:  # pragma: no cover
        return f"ColumnVector({self.dtype!r}, shape={getattr(self.data, 'shape', None)})"

    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    def with_data(self, data: Array, valid: Union[Array, None, type(...)] = ...) -> "ColumnVector":
        """New vector with replaced data; ``valid=...`` keeps the old mask."""
        v = self.valid if valid is ... else valid
        return ColumnVector(data, self.dtype, v, self.dictionary)

    def valid_or_true(self) -> Array:
        if self.valid is not None:
            return self.valid
        return _xp(self.data).ones(self.data.shape[0], dtype=bool)

    # ---- host/device movement ------------------------------------------
    def to_device(self) -> "ColumnVector":
        return ColumnVector(jnp.asarray(self.data), self.dtype,
                            None if self.valid is None else jnp.asarray(self.valid),
                            self.dictionary)

    def to_host(self) -> "ColumnVector":
        return ColumnVector(np.asarray(self.data), self.dtype,
                            None if self.valid is None else np.asarray(self.valid),
                            self.dictionary)

    def to_pylist(self, row_valid: Optional[Array] = None) -> List[Any]:
        """Decode to Python objects (None for NULL); for collect()."""
        data = np.asarray(self.data)
        valid = np.ones(len(data), bool) if self.valid is None else np.asarray(self.valid)
        if row_valid is not None:
            sel = np.asarray(row_valid)
            data, valid = data[sel], valid[sel]
        out: List[Any] = []
        dt = self.dtype
        if self.dictionary is not None and len(data):
            global _LATE_MATERIALIZED_ROWS
            _LATE_MATERIALIZED_ROWS += len(data)
        for i in range(len(data)):
            if not valid[i]:
                out.append(None)
            elif isinstance(dt, T.ArrayType):
                ed = dt.element_type
                row = data[i]
                if ed.is_fractional:
                    live = row[~np.isnan(row.astype(np.float64))]
                    out.append([float(x) for x in live])
                elif ed.is_string:
                    codes = row[row >= 0]
                    out.append([
                        self.dictionary[int(c)] if self.dictionary is not None
                        and 0 <= int(c) < len(self.dictionary) else None
                        for c in codes])
                else:
                    sent = dt.element_sentinel()
                    live = row[row != sent]
                    out.append([int(x) for x in live])
            elif dt.is_string or isinstance(dt, T.BinaryType):
                code = int(data[i])
                out.append(self.dictionary[code] if (self.dictionary is not None and 0 <= code < len(self.dictionary)) else None)
            elif isinstance(dt, T.BooleanType):
                out.append(bool(data[i]))
            elif isinstance(dt, T.DecimalType):
                out.append(float(data[i]) / (10 ** dt.scale))
            elif isinstance(dt, T.DateType):
                out.append(np.datetime64(int(data[i]), "D").astype("datetime64[D]").item())
            elif isinstance(dt, T.TimestampType):
                out.append(np.datetime64(int(data[i]), "us").item())
            elif dt.is_fractional:
                out.append(float(data[i]))
            else:
                out.append(int(data[i]))
        return out


class ColumnBatch:
    """A fixed-capacity batch of columns plus a row-existence mask.

    Registered as a JAX pytree: arrays are leaves; names/dtypes/dictionaries/
    capacity are static aux data, so operator pipelines jit cleanly.
    """

    # _cache_uid: lazily-assigned identity for plan cache keys
    # (memory.py) -- id() could be recycled after GC
    __slots__ = ("names", "vectors", "row_valid", "capacity",
                 "_cache_uid")

    def __init__(self, names: Sequence[str], vectors: Sequence[ColumnVector],
                 row_valid: Optional[Array], capacity: int):
        assert len(names) == len(vectors)
        self.names = list(names)
        self.vectors = list(vectors)
        self.row_valid = row_valid
        self.capacity = capacity

    # -- constructors -----------------------------------------------------
    @staticmethod
    def from_arrays(data: Dict[str, Any], num_rows: Optional[int] = None,
                    capacity: Optional[int] = None,
                    schema: Optional[T.StructType] = None) -> "ColumnBatch":
        """Build from host arrays / lists; pads to a static capacity."""
        names = list(data.keys())
        if num_rows is None:
            num_rows = len(next(iter(data.values()))) if names else 0
        cap = capacity or pad_capacity(num_rows)
        if cap < num_rows:
            raise ValueError(f"capacity {cap} < num_rows {num_rows}")
        vectors: List[ColumnVector] = []
        for name in names:
            raw = data[name]
            dt = schema[name].dataType if schema is not None else None
            vec = _ingest_column(raw, num_rows, cap, dt)
            vectors.append(vec)
        row_valid = None
        if cap != num_rows:
            rv = np.zeros(cap, dtype=bool)
            rv[:num_rows] = True
            row_valid = rv
        return ColumnBatch(names, vectors, row_valid, cap)

    @staticmethod
    def from_pandas(df, capacity: Optional[int] = None) -> "ColumnBatch":
        import pandas as pd
        data = {}
        for name in df.columns:
            s = df[name]
            if s.dtype == object or str(s.dtype) in ("string", "str"):
                na = s.isna().to_numpy()
                data[str(name)] = [None if na[i] else v for i, v in enumerate(s.tolist())]
            elif str(s.dtype).startswith(("Int", "Float", "boolean")):  # nullable ext dtypes
                na = s.isna().to_numpy()
                data[str(name)] = [None if na[i] else v for i, v in enumerate(s.tolist())]
            else:
                data[str(name)] = s.to_numpy()
        return ColumnBatch.from_arrays(data, num_rows=len(df), capacity=capacity)

    @staticmethod
    def empty(schema: T.StructType, capacity: int = MIN_CAPACITY) -> "ColumnBatch":
        vectors = []
        for f in schema.fields:
            arr = np.zeros(capacity, dtype=f.dataType.np_dtype)
            d = () if (f.dataType.is_string or isinstance(f.dataType, T.BinaryType)) else None
            vectors.append(ColumnVector(arr, f.dataType, None, d))
        return ColumnBatch(schema.names, vectors, np.zeros(capacity, bool), capacity)

    # -- schema & access --------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        return T.StructType([
            T.StructField(n, v.dtype, v.valid is not None)
            for n, v in zip(self.names, self.vectors)
        ])

    def column(self, name: str) -> ColumnVector:
        return self.vectors[self.names.index(name)]

    def __contains__(self, name: str) -> bool:
        return name in self.names

    def with_columns(self, names: Sequence[str], vectors: Sequence[ColumnVector]) -> "ColumnBatch":
        return ColumnBatch(list(names), list(vectors), self.row_valid, self.capacity)

    def row_valid_or_true(self) -> Array:
        if self.row_valid is not None:
            return self.row_valid
        # probe device residency without touching .data — that would
        # materialize a lazy RunColumnVector (host) or expand a
        # PlaneColumnVector (device) before any operator asked for rows
        def _probe(v):
            if isinstance(v, PlaneColumnVector):
                return v.plane_values if v._dense is None else v._dense
            if isinstance(v, RunColumnVector):
                return v._dense
            return v.data
        xp = jnp if any(isinstance(_probe(v), jax.Array)
                        for v in self.vectors) else np
        return xp.ones(self.capacity, dtype=bool)

    def num_rows(self):
        """Number of live rows — a traced scalar under jit, int on host."""
        if self.row_valid is None:
            return self.capacity
        xp = _xp(self.row_valid)
        return xp.sum(self.row_valid)

    # -- movement ---------------------------------------------------------
    def to_device(self) -> "ColumnBatch":
        rv = None if self.row_valid is None else jnp.asarray(self.row_valid)
        return ColumnBatch(self.names, [v.to_device() for v in self.vectors], rv, self.capacity)

    def to_host(self) -> "ColumnBatch":
        rv = None if self.row_valid is None else np.asarray(self.row_valid)
        return ColumnBatch(self.names, [v.to_host() for v in self.vectors], rv, self.capacity)

    # -- output -----------------------------------------------------------
    def to_pylist(self) -> List[tuple]:
        """Rows as tuples (collect() decode path)."""
        rv = None if self.row_valid is None else np.asarray(self.row_valid)
        cols = [v.to_pylist(rv) for v in self.vectors]
        if not cols:
            n = int(rv.sum()) if rv is not None else self.capacity
            return [() for _ in range(n)]
        return list(zip(*cols))

    def to_pandas(self):
        import pandas as pd
        rows = self.to_pylist()
        return pd.DataFrame(rows, columns=self.names)

    def __repr__(self) -> str:  # pragma: no cover
        return f"ColumnBatch({self.schema.simpleString()}, capacity={self.capacity})"


class RunColumnVector(ColumnVector):
    """Run-length encoded column: ``(run_values, run_lengths)`` standing in
    for a dense array of ``sum(run_lengths)`` elements.

    The dense form is produced lazily on the first ``.data`` access (counted
    in ``runs_materialized``); run-aware operators read ``run_values`` /
    ``run_lengths`` directly and never pay the expansion.  Everything else —
    validity, dtype, dictionary, pytree participation — behaves exactly like
    a dense ``ColumnVector``, so the lazy form is a drop-in safety net: any
    code path that was not taught about runs simply materializes."""

    __slots__ = ("run_values", "run_lengths", "_n", "_dense")

    def __init__(self, run_values: Array, run_lengths: Array,
                 dtype: T.DataType, valid: Optional[Array] = None,
                 dictionary: Optional[Tuple[str, ...]] = None):
        self.run_values = np.asarray(run_values)
        self.run_lengths = np.asarray(run_lengths, dtype=np.int64)
        self._n = int(self.run_lengths.sum())
        self._dense = None
        self.dtype = dtype
        self.valid = valid
        self.dictionary = dictionary

    def __repr__(self) -> str:  # pragma: no cover
        return (f"RunColumnVector({self.dtype!r}, n={self._n}, "
                f"runs={len(self.run_values)}, "
                f"materialized={self._dense is not None})")

    @property
    def is_materialized(self) -> bool:
        return self._dense is not None

    @property
    def data(self) -> Array:
        # shadows the parent's `data` slot: expansion happens here, once
        if self._dense is None:
            global _RUNS_MATERIALIZED
            _RUNS_MATERIALIZED += self._n
            self._dense = np.repeat(self.run_values, self.run_lengths)
        return self._dense

    @property
    def capacity(self) -> int:
        return self._n

    def valid_or_true(self) -> Array:
        if self.valid is not None:
            return self.valid
        return np.ones(self._n, dtype=bool)

    def to_host(self) -> "ColumnVector":
        return self  # run tables are always host arrays

    def to_device(self) -> "ColumnVector":
        if self._dense is None:
            # expand ON DEVICE: the run table crosses as two small
            # arrays and the repeat runs compiled (shape-static via
            # total_repeat_length) — the counted host expansion in
            # ``.data`` is reserved for operators that genuinely need
            # dense HOST rows
            data = jnp.repeat(jnp.asarray(self.run_values),
                              jnp.asarray(self.run_lengths),
                              total_repeat_length=self._n)
        else:
            data = jnp.asarray(self._dense)
        return ColumnVector(data, self.dtype,
                            None if self.valid is None
                            else jnp.asarray(self.valid),
                            self.dictionary)

    def with_run_values(self, run_values: Array,
                        dictionary: Union[Tuple[str, ...], None,
                                          type(...)] = ...) -> "RunColumnVector":
        """New run vector with remapped run values (same run structure) —
        the seam dictionary-code remapping uses to stay run-preserving."""
        d = self.dictionary if dictionary is ... else dictionary
        return RunColumnVector(run_values, self.run_lengths, self.dtype,
                               self.valid, d)


def unmaterialized_runs(v: ColumnVector) -> Optional[RunColumnVector]:
    """``v`` if it is a run-encoded column whose dense form was never built
    (so run-granularity work is still a win), else None."""
    if isinstance(v, RunColumnVector) and not v.is_materialized:
        return v
    return None


class PlaneColumnVector(ColumnVector):
    """Fixed-capacity DEVICE form of a run table — the shape-stable pytree
    citizen that lets compressed columns cross the jit boundary.

    ``plane_values`` (run values zero-padded to the plane capacity, a
    ``pad_capacity`` bucket of the run count) and ``plane_lengths``
    (int64 run lengths, zero-padded) are the two pytree leaves; the dense
    capacity they stand in for is static aux.  Real runs are exactly the
    ``lengths > 0`` prefix — RLE never emits zero-length runs, so the
    zero padding is unambiguous.  Taught jit-lane kernels (segmented
    filter, keyless count/sum/min/max, bare-column project) read the
    plane directly; any untaught operator that asks for ``.data`` gets a
    memoized in-trace searchsorted-gather expansion (counted in
    ``run_plane_expansions``) — byte-identical, fused and dead-code
    eliminated by XLA when unused, and it never touches the host
    ``runs_materialized`` counter.  Planes are a LOCAL stage form: mesh
    (shard_map) stages never receive them, because slicing a plane along
    the run axis would not slice the rows it encodes."""

    __slots__ = ("plane_values", "plane_lengths", "n_runs", "_capacity",
                 "_dense")

    def __init__(self, plane_values: Array, plane_lengths: Array,
                 dtype: T.DataType, capacity: int,
                 valid: Optional[Array] = None,
                 dictionary: Optional[Tuple[str, ...]] = None,
                 n_runs: Optional[int] = None):
        self.plane_values = plane_values
        self.plane_lengths = plane_lengths
        self.n_runs = None if n_runs is None else int(n_runs)
        self._capacity = int(capacity)
        self._dense = None
        self.dtype = dtype
        self.valid = valid
        self.dictionary = dictionary

    @classmethod
    def from_runs(cls, rv: RunColumnVector,
                  plane_cap: int, device: bool = True) -> "PlaneColumnVector":
        """Pad a host run table into a plane of capacity ``plane_cap``
        (a ``pad_capacity`` bucket ≥ the run count)."""
        nr = len(rv.run_values)
        values = np.zeros(plane_cap, rv.run_values.dtype)
        values[:nr] = rv.run_values
        lengths = np.zeros(plane_cap, np.int64)
        lengths[:nr] = rv.run_lengths
        if device:
            values, lengths = jnp.asarray(values), jnp.asarray(lengths)
        return cls(values, lengths, rv.dtype, rv.capacity, rv.valid,
                   rv.dictionary, n_runs=nr)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"PlaneColumnVector({self.dtype!r}, capacity={self._capacity},"
                f" plane={int(self.plane_values.shape[0])},"
                f" runs={self.n_runs}, expanded={self._dense is not None})")

    @property
    def plane_capacity(self) -> int:
        return int(self.plane_values.shape[0])

    @property
    def is_expanded(self) -> bool:
        return self._dense is not None

    @property
    def capacity(self) -> int:
        return self._capacity

    @property
    def data(self) -> Array:
        # shadows the parent's `data` slot: the untaught-operator safety
        # net — one memoized in-trace expansion per trace, never counted
        # as host materialization
        if self._dense is None:
            global _RUN_PLANE_EXPANSIONS
            _RUN_PLANE_EXPANSIONS += 1
            from . import kernels
            xp = jnp if isinstance(self.plane_values, jax.Array) else np
            self._dense = kernels.run_expand(
                xp, self.plane_values, self.plane_lengths, self._capacity)
        return self._dense

    def valid_or_true(self) -> Array:
        if self.valid is not None:
            return self.valid
        xp = jnp if isinstance(self.plane_values, jax.Array) else np
        return xp.ones(self._capacity, dtype=bool)

    def to_device(self) -> "ColumnVector":
        if isinstance(self.plane_values, jax.Array):
            return self
        return PlaneColumnVector(
            jnp.asarray(self.plane_values), jnp.asarray(self.plane_lengths),
            self.dtype, self._capacity,
            None if self.valid is None else jnp.asarray(self.valid),
            self.dictionary, n_runs=self.n_runs)

    def to_host(self) -> "ColumnVector":
        # leaving the device lane: hand back a dense host vector (planes
        # have no host consumers; the expansion is the memoized one)
        return ColumnVector(np.asarray(self.data), self.dtype,
                            None if self.valid is None
                            else np.asarray(self.valid),
                            self.dictionary)


def unexpanded_plane(v: ColumnVector) -> Optional[PlaneColumnVector]:
    """``v`` if it is a run plane whose dense form was never demanded (so
    plane-granularity work is still a win), else None."""
    if isinstance(v, PlaneColumnVector) and v._dense is None:
        return v
    return None


class PrebuiltColumn:
    """Already-decoded column (data array + engine type + validity) — the
    vectorized readers hand these to ``from_arrays`` so nullable numeric
    columns never round-trip through Python objects."""

    __slots__ = ("data", "dtype", "valid")

    def __init__(self, data: np.ndarray, dtype: T.DataType,
                 valid: Optional[np.ndarray]):
        self.data = data
        self.dtype = dtype
        self.valid = valid

    def __len__(self):
        return len(self.data)


def _ingest_column(raw: Any, num_rows: int, cap: int,
                   dtype: Optional[T.DataType]) -> ColumnVector:
    """Convert one host column (list/ndarray) into a padded ColumnVector."""
    dictionary: Optional[Tuple[str, ...]] = None
    valid: Optional[np.ndarray] = None

    if isinstance(raw, PrebuiltColumn):
        data = raw.data
        valid = raw.valid
        if len(data) < cap:
            data = np.concatenate(
                [data, np.zeros(cap - len(data), data.dtype)])
            if valid is not None:
                valid = np.concatenate(
                    [valid, np.zeros(cap - len(raw.valid), bool)])
        return ColumnVector(data, raw.dtype, valid, None)

    # fixed-width vector column (ML feature vectors): 2D data, ArrayType
    if isinstance(raw, np.ndarray) and raw.ndim == 2:
        dt = dtype if isinstance(dtype, T.ArrayType) else T.ArrayType(T.float64)
        data = raw.astype(dt.element_type.np_dtype)
        if len(data) < cap:
            pad = np.zeros((cap - len(data),) + data.shape[1:], data.dtype)
            data = np.concatenate([data, pad])
        return ColumnVector(data, dt, None, None)
    if (not isinstance(raw, np.ndarray) and len(raw)
            and isinstance(next((v for v in raw if v is not None), None),
                           (list, tuple, np.ndarray))):
        values = [([] if v is None else list(v)) for v in raw]
        width = max((len(v) for v in values), default=1) or 1
        nulls = np.fromiter((v is None for v in raw), bool, count=len(values))
        if isinstance(dtype, T.ArrayType):
            ed = dtype.element_type
        else:
            all_int = all(
                isinstance(x, (int, np.integer))
                and not isinstance(x, bool)
                for v in values for x in v if x is not None)
            ed = T.int64 if all_int and any(len(v) for v in values) \
                else T.float64
        dt = dtype if isinstance(dtype, T.ArrayType) else T.ArrayType(ed)
        # ragged tails / None elements carry the ELEMENT SENTINEL (NaN for
        # fractional, element_sentinel() for integral) — the device layout
        # to_pylist/array kernels treat as dead, never silent zeros
        sent = np.nan if ed.is_fractional else dt.element_sentinel()
        mat = np.full((len(values), width), sent, ed.np_dtype)
        for i, v in enumerate(values):
            for j, x in enumerate(v):
                if x is not None and not (isinstance(x, float)
                                          and np.isnan(x)):
                    mat[i, j] = x
        if len(mat) < cap:
            mat = np.concatenate(
                [mat, np.full((cap - len(mat), width), sent, ed.np_dtype)])
        valid = None if not nulls.any() else np.concatenate(
            [~nulls, np.zeros(cap - len(values), bool)])
        return ColumnVector(mat, dt, valid, None)

    if isinstance(raw, np.ndarray) and raw.dtype.kind not in ("O", "U", "S"):
        if raw.dtype.kind == "M":  # datetime64
            if isinstance(dtype, T.DateType):
                data = raw.astype("datetime64[D]").astype(np.int32)
                dt = dtype
            else:
                data = raw.astype("datetime64[us]").astype(np.int64)
                dt = dtype or T.timestamp
        elif isinstance(dtype, T.DecimalType):
            dt = dtype
            fl = raw.astype(np.float64)
            nan = np.isnan(fl)
            data = np.round(np.where(nan, 0.0, fl) * 10 ** dt.scale).astype(np.int64)
            if nan.any():
                valid = ~nan
        elif raw.dtype.kind == "f":
            dt = dtype or T.np_dtype_to_engine(raw.dtype)
            nan = np.isnan(raw)
            data = np.where(nan, 0.0, raw).astype(dt.np_dtype)
            if nan.any():
                valid = ~nan
        else:
            dt = dtype or T.np_dtype_to_engine(raw.dtype)
            data = raw.astype(dt.np_dtype)
    else:
        values = list(raw)
        nulls = np.fromiter((v is None or (isinstance(v, float) and np.isnan(v)) for v in values),
                            dtype=bool, count=len(values))
        sample = next((v for v in values if v is not None), None)
        dt = dtype or (T.infer_type(sample) if sample is not None else T.null_type)
        if dt.is_string or isinstance(dt, T.BinaryType):
            # binary keeps bytes in the dictionary; strings coerce via str()
            conv = (lambda v: v) if isinstance(dt, T.BinaryType) else str
            codes, dictionary = encode_strings(
                [None if nulls[i] else conv(values[i]) for i in range(len(values))])
            data = np.where(codes < 0, 0, codes).astype(np.int32)
            if (codes < 0).any():
                valid = codes >= 0
        elif isinstance(dt, T.DecimalType):
            scale = 10 ** dt.scale
            data = np.fromiter(
                (0 if nulls[i] else int(round(float(values[i]) * scale)) for i in range(len(values))),
                dtype=np.int64, count=len(values))
            if nulls.any():
                valid = ~nulls
        elif isinstance(dt, T.DateType):
            data = np.fromiter(
                (0 if nulls[i] else np.datetime64(values[i], "D").astype(np.int32) for i in range(len(values))),
                dtype=np.int32, count=len(values))
            if nulls.any():
                valid = ~nulls
        elif isinstance(dt, T.TimestampType):
            data = np.fromiter(
                (0 if nulls[i] else np.datetime64(values[i], "us").astype(np.int64) for i in range(len(values))),
                dtype=np.int64, count=len(values))
            if nulls.any():
                valid = ~nulls
        else:
            data = np.fromiter(
                (dt.null_sentinel() if nulls[i] else values[i] for i in range(len(values))),
                dtype=dt.np_dtype, count=len(values))
            if nulls.any():
                valid = ~nulls

    if len(data) < cap:
        pad = np.zeros(cap - len(data), dtype=data.dtype)
        data = np.concatenate([data, pad])
        if valid is not None:
            valid = np.concatenate([valid, np.zeros(cap - len(valid), bool)])
    return ColumnVector(data, dt, valid, dictionary)


# ---------------------------------------------------------------------------
# pytree registration — makes ColumnBatch traceable end-to-end
# ---------------------------------------------------------------------------

def _batch_flatten(b: ColumnBatch):
    # a run plane contributes its (values, lengths) pair as the data child
    # (tuples are pytrees, so both pad to leaves); the per-vector plane
    # marker in aux carries n_runs (-1 when unknown) so unflatten rebuilds
    # the plane instead of a dense vector
    datas, planes = [], []
    for v in b.vectors:
        if isinstance(v, PlaneColumnVector):
            datas.append((v.plane_values, v.plane_lengths))
            planes.append(-1 if v.n_runs is None else v.n_runs)
        else:
            datas.append(v.data)
            planes.append(None)
    children = (datas, [v.valid for v in b.vectors], b.row_valid)
    aux = (tuple(b.names),
           tuple(v.dtype for v in b.vectors),
           tuple(v.dictionary for v in b.vectors),
           b.capacity,
           tuple(planes))
    return children, aux


def _batch_unflatten(aux, children):
    if len(aux) == 5:
        names, dtypes, dicts, capacity, planes = aux
    else:  # pre-plane aux (serialized treedefs): no plane vectors
        names, dtypes, dicts, capacity = aux
        planes = (None,) * len(names)
    datas, valids, row_valid = children
    # Inside shard_map/vmap the leaves are per-shard slices whose length
    # differs from the stored aux capacity — trust the arrays when possible.
    # Plane children are (values, lengths) tuples: their length is the
    # plane capacity, not the dense capacity, so they never vote here.
    for leaf in list(datas) + [row_valid]:
        if isinstance(leaf, tuple):
            continue
        shape = getattr(leaf, "shape", None)
        if shape is not None and len(shape) >= 1:
            capacity = int(shape[0])
            break
    vectors = []
    for d, v, t, dic, pl in zip(datas, valids, dtypes, dicts, planes):
        if pl is not None:
            pv, plen = d
            vectors.append(PlaneColumnVector(
                pv, plen, t, capacity, v, dic,
                n_runs=None if pl < 0 else pl))
        else:
            vectors.append(ColumnVector(d, t, v, dic))
    b = ColumnBatch.__new__(ColumnBatch)
    b.names = list(names)
    b.vectors = vectors
    b.row_valid = row_valid
    b.capacity = capacity
    return b


jax.tree_util.register_pytree_node(ColumnBatch, _batch_flatten, _batch_unflatten)
