"""Legacy RDD-based MLlib compat layer (``mllib/`` in the reference).

The reference freezes this API (RDD-based, `mllib/.../clustering/KMeans.scala`
`train()` entry points) in favor of DataFrame `ml/` pipelines; here the
classic surface delegates to the TPU-first `spark_tpu.ml` implementations.
Inputs are RDDs of feature rows (lists/tuples/numpy) or LabeledPoint;
outputs are the corresponding ml models.  New code should use
``spark_tpu.ml`` directly — see docs/DECISIONS.md.
"""

from __future__ import annotations

from typing import Any, List, Sequence

import numpy as np


class LabeledPoint:
    """(label, features) pair (`mllib/regression/LabeledPoint.scala`)."""

    __slots__ = ("label", "features")

    def __init__(self, label: float, features: Sequence[float]):
        self.label = float(label)
        self.features = np.asarray(features, dtype=np.float64)

    def __repr__(self):
        return f"LabeledPoint({self.label}, {self.features.tolist()})"


def _session():
    from ..sql.session import SparkSession
    s = SparkSession.getActiveSession()
    if s is None:
        s = SparkSession.builder.getOrCreate()
    return s


def _features_df(rdd_or_rows, with_label: bool):
    rows = rdd_or_rows.collect() if hasattr(rdd_or_rows, "collect") \
        else list(rdd_or_rows)
    if not rows:
        raise ValueError("empty training data")
    feats: List[np.ndarray] = []
    labels: List[float] = []
    for r in rows:
        if isinstance(r, LabeledPoint):
            labels.append(r.label)
            feats.append(r.features)
        elif with_label:
            labels.append(float(r[0]))
            feats.append(np.asarray(r[1], dtype=np.float64))
        else:
            feats.append(np.asarray(r, dtype=np.float64))
    import pandas as pd
    data = {"features": [list(map(float, f)) for f in feats]}
    if with_label:
        data["label"] = labels
    return _session().createDataFrame(pd.DataFrame(data))


class KMeans:
    @staticmethod
    def train(rdd, k: int, maxIterations: int = 20, seed: int = 0):
        from ..ml.clustering import KMeans as MLKMeans
        df = _features_df(rdd, with_label=False)
        return MLKMeans(k=k, maxIter=maxIterations, seed=seed,
                        featuresCol="features").fit(df)


class LogisticRegressionWithLBFGS:
    @staticmethod
    def train(rdd, iterations: int = 100, regParam: float = 0.0):
        from ..ml.classification import LogisticRegression
        df = _features_df(rdd, with_label=True)
        return LogisticRegression(maxIter=iterations, regParam=regParam
                                  ).fit(df)


class LinearRegressionWithSGD:
    @staticmethod
    def train(rdd, iterations: int = 100, regParam: float = 0.0):
        from ..ml.regression import LinearRegression
        df = _features_df(rdd, with_label=True)
        return LinearRegression(maxIter=iterations, regParam=regParam
                                ).fit(df)


class NaiveBayes:
    @staticmethod
    def train(rdd, lambda_: float = 1.0):
        from ..ml.classification import NaiveBayes as MLNB
        df = _features_df(rdd, with_label=True)
        return MLNB(smoothing=lambda_).fit(df)
