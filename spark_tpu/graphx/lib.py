"""Graph algorithms (`graphx/lib/`): PageRank, connected components,
shortest paths, triangle count — each a handful of segment-op supersteps."""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import ops as jops

from .graph import Graph

#: distance reported for unreachable vertices (overflow-safe sentinel)
UNREACHABLE = int(np.iinfo(np.int64).max // 4)


def _vertex_index(vids: np.ndarray, vid: int):
    """Index of an external vertex id; handles unsorted id arrays (the
    public Graph constructor does not require sorted ids)."""
    hits = np.nonzero(vids == vid)[0]
    return int(hits[0]) if len(hits) else None


def page_rank(graph: Graph, num_iter: int = 20, reset_prob: float = 0.15,
              tol: float = 0.0) -> jnp.ndarray:
    """Reference-convention PageRank (`lib/PageRank.scala`): ranks start at
    1.0 and update as resetProb + (1-resetProb) * sum(incoming rank/outDeg)
    — unnormalized, matching GraphX's output values."""
    n = graph.num_vertices
    out_deg = graph.out_degrees.astype(jnp.float64)
    safe_deg = jnp.maximum(out_deg, 1)
    src, dst = graph.src, graph.dst

    @jax.jit
    def step(ranks):
        contrib = (ranks / safe_deg)[src]
        sums = jops.segment_sum(contrib, dst, num_segments=n)
        new = reset_prob + (1.0 - reset_prob) * sums
        delta = jnp.max(jnp.abs(new - ranks))
        return new, delta

    ranks = jnp.ones(n, jnp.float64)
    for _ in range(num_iter):
        ranks, delta = step(ranks)
        if tol > 0.0 and float(delta) < tol:
            break
    return ranks


pageRank = page_rank


def connected_components(graph: Graph, max_iterations: int = 64
                         ) -> jnp.ndarray:
    """Min-label propagation (`lib/ConnectedComponents.scala`): every
    vertex converges to the smallest vertex ID in its component."""
    n = graph.num_vertices
    src, dst = graph.src, graph.dst

    @jax.jit
    def step(cc):
        # isolated vertices get the identity (int64 max) from empty
        # segments; minimum() with the own label already handles it
        to_dst = jops.segment_min(cc[src], dst, num_segments=n)
        to_src = jops.segment_min(cc[dst], src, num_segments=n)
        new = jnp.minimum(cc, jnp.minimum(to_dst, to_src))
        changed = jnp.sum((new != cc).astype(jnp.int64))
        return new, changed

    cc = graph.vertex_ids
    for _ in range(max_iterations):
        cc, changed = step(cc)
        if int(changed) == 0:
            break
    return cc


connectedComponents = connected_components


def shortest_paths(graph: Graph, landmarks: Sequence[int],
                   max_iterations: int = 64) -> Dict[int, jnp.ndarray]:
    """Unweighted BFS distances to each landmark
    (`lib/ShortestPaths.scala`); unreachable = UNREACHABLE (int64 max/4,
    far above any real distance and overflow-safe under the +1 relax)."""
    n = graph.num_vertices
    src, dst = graph.src, graph.dst
    vids = np.asarray(graph.vertex_ids)
    INF = UNREACHABLE

    @jax.jit
    def step(dist):
        # relax over both directions (reference treats edges as directed
        # toward the landmark set update; we propagate undirected like its
        # default usage in tests).  Empty segments yield int64 max; cap
        # before +1 so isolated vertices cannot overflow-wrap negative.
        d_dst = jops.segment_min(dist[src], dst, num_segments=n)
        d_src = jops.segment_min(dist[dst], src, num_segments=n)
        best = jnp.minimum(jnp.minimum(d_dst, d_src), INF)
        relaxed = jnp.minimum(dist, best + 1)
        changed = jnp.sum((relaxed != dist).astype(jnp.int64))
        return relaxed, changed

    out: Dict[int, jnp.ndarray] = {}
    for lm in landmarks:
        idx = _vertex_index(vids, lm)
        if idx is None:
            raise ValueError(f"landmark {lm} is not a vertex")
        dist = jnp.full(n, INF, jnp.int64).at[idx].set(0)
        for _ in range(max_iterations):
            dist, changed = step(dist)
            if int(changed) == 0:
                break
        out[lm] = dist
    return out


shortestPaths = shortest_paths


def triangle_count(graph: Graph) -> jnp.ndarray:
    """Per-vertex triangle counts (`lib/TriangleCount.scala`): canonical
    undirected edges, neighbor-set intersection per edge, summed to both
    endpoints.  Host adjacency build + vectorized membership."""
    src = np.asarray(graph.src)
    dst = np.asarray(graph.dst)
    n = graph.num_vertices
    # canonicalize: undirected unique edges, no self loops
    a, b = np.minimum(src, dst), np.maximum(src, dst)
    keep = a != b
    edges = np.unique(np.stack([a[keep], b[keep]], 1), axis=0)
    adj = [set() for _ in range(n)]
    for u, v in edges:
        adj[u].add(v)
        adj[v].add(u)
    counts = np.zeros(n, np.int64)
    for u, v in edges:
        common = len(adj[u] & adj[v])
        counts[u] += common
        counts[v] += common
    # each triangle contributes twice per vertex (once per incident edge
    # of the triangle at that vertex) -> halve
    return jnp.asarray(counts // 2)


triangleCount = triangle_count
