"""Graph processing on device segment ops (the GraphX analog).

The reference (`graphx/.../Graph.scala`, `Pregel.scala:59`) builds graphs
on RDDs with per-superstep joins; here a graph IS a set of device arrays
(dense-indexed vertices, edge endpoint indices), `aggregateMessages` is a
vectorized edge computation + `jax.ops.segment_*` reduction, and Pregel
supersteps are host-driven iterations of one jitted step — BSP where the
barrier is the XLA program boundary.
"""

from .graph import Edge, Graph, pregel                       # noqa: F401
from .lib import (                                           # noqa: F401
    connected_components, page_rank, shortest_paths, triangle_count,
)
