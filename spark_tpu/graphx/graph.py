"""Graph + aggregateMessages + Pregel.

Design mapping from the reference:
- `Graph[VD, ED]` (`graphx/.../Graph.scala`)           -> dense vertex
  arrays + edge endpoint INDEX arrays (vertex ids remapped once at
  construction; `PartitionStrategy` 2D partitioning has no analog needed:
  one device holds the arrays, the mesh dimension comes later via sharded
  segment ops).
- `aggregateMessages(sendMsg, mergeMsg)` (`GraphOps`)  -> a vectorized
  message function over (src attrs, dst attrs, edge attrs) arrays +
  `segment_sum/min/max` by destination; no triplet iterator.
- `Pregel.scala:59`                                    -> host loop over
  one jitted superstep; active-vertex semantics via a has-message mask.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import ops as jops

Array = Any

_REDUCE = {
    "sum": jops.segment_sum,
    "min": jops.segment_min,
    "max": jops.segment_max,
}


class Edge(NamedTuple):
    """srcId, dstId, attr — constructor-compat with the reference Edge."""

    srcId: int
    dstId: int
    attr: Any = 1.0


class Graph:
    """Immutable graph over device arrays.

    vertex_ids: (n,) int64 external ids (unique); vertex/edge attrs are
    name -> (n,)/(m,) arrays; src/dst hold DENSE indices into vertex_ids.
    """

    def __init__(self, vertex_ids: Array, vertex_attrs: Dict[str, Array],
                 src: Array, dst: Array,
                 edge_attrs: Optional[Dict[str, Array]] = None):
        self.vertex_ids = jnp.asarray(vertex_ids, jnp.int64)
        self.vertex_attrs = {k: jnp.asarray(v)
                             for k, v in (vertex_attrs or {}).items()}
        self.src = jnp.asarray(src, jnp.int32)
        self.dst = jnp.asarray(dst, jnp.int32)
        self.edge_attrs = {k: jnp.asarray(v)
                           for k, v in (edge_attrs or {}).items()}

    # -- construction -----------------------------------------------------
    @staticmethod
    def from_edge_tuples(edges, default_attr=1.0,
                         vertex_attrs: Optional[Dict[str, Array]] = None
                         ) -> "Graph":
        """Build from (srcId, dstId[, attr]) tuples / arrays; vertex set =
        union of endpoint ids (`Graph.fromEdgeTuples`)."""
        es = list(edges)
        srcs = np.array([e[0] for e in es], np.int64)
        dsts = np.array([e[1] for e in es], np.int64)
        attr = np.array([e[2] if len(e) > 2 else default_attr for e in es])
        vids = np.unique(np.concatenate([srcs, dsts]))
        src_idx = np.searchsorted(vids, srcs)
        dst_idx = np.searchsorted(vids, dsts)
        return Graph(vids, vertex_attrs or {}, src_idx, dst_idx,
                     {"attr": attr})

    fromEdgeTuples = from_edge_tuples

    @staticmethod
    def from_edges(edges, default_vertex_attr=None) -> "Graph":
        g = Graph.from_edge_tuples(
            [(e.srcId, e.dstId, e.attr) for e in edges])
        if default_vertex_attr is not None:
            g.vertex_attrs["attr"] = jnp.full(
                (g.num_vertices,), default_vertex_attr)
        return g

    fromEdges = from_edges

    # -- basics -----------------------------------------------------------
    @property
    def num_vertices(self) -> int:
        return int(self.vertex_ids.shape[0])

    numVertices = num_vertices

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    numEdges = num_edges

    @property
    def out_degrees(self) -> Array:
        return jops.segment_sum(jnp.ones_like(self.src, jnp.int64),
                                self.src, num_segments=self.num_vertices)

    outDegrees = out_degrees

    @property
    def in_degrees(self) -> Array:
        return jops.segment_sum(jnp.ones_like(self.dst, jnp.int64),
                                self.dst, num_segments=self.num_vertices)

    inDegrees = in_degrees

    @property
    def degrees(self) -> Array:
        return self.out_degrees + self.in_degrees

    def reverse(self) -> "Graph":
        return Graph(self.vertex_ids, self.vertex_attrs, self.dst, self.src,
                     self.edge_attrs)

    def map_vertices(self, fn: Callable[[Dict[str, Array]], Dict[str, Array]]
                     ) -> "Graph":
        return Graph(self.vertex_ids, fn(dict(self.vertex_attrs)),
                     self.src, self.dst, self.edge_attrs)

    mapVertices = map_vertices

    def map_edges(self, fn) -> "Graph":
        return Graph(self.vertex_ids, self.vertex_attrs, self.src, self.dst,
                     fn(dict(self.edge_attrs)))

    mapEdges = map_edges

    def subgraph(self, edge_mask: Array) -> "Graph":
        """Edges where mask holds (vertex set unchanged, like the
        reference's epred-only subgraph)."""
        mask = np.asarray(edge_mask)
        return Graph(self.vertex_ids, self.vertex_attrs,
                     np.asarray(self.src)[mask], np.asarray(self.dst)[mask],
                     {k: np.asarray(v)[mask]
                      for k, v in self.edge_attrs.items()})

    # -- the message primitive -------------------------------------------
    def aggregate_messages(self, send: Callable, merge: str = "sum",
                           to: str = "dst") -> Array:
        """`aggregateMessages`: `send(src_attrs, dst_attrs, edge_attrs)`
        returns one message ARRAY of shape (num_edges, ...); messages
        reduce per `to`-vertex with the named kind.  Vertices receiving no
        message get the reduction identity (mask with degrees if needed).
        """
        srcs = {k: v[self.src] for k, v in self.vertex_attrs.items()}
        dsts = {k: v[self.dst] for k, v in self.vertex_attrs.items()}
        msg = send(srcs, dsts, self.edge_attrs)
        seg = self.dst if to == "dst" else self.src
        return _REDUCE[merge](msg, seg, num_segments=self.num_vertices)

    aggregateMessages = aggregate_messages

    # -- interop ----------------------------------------------------------
    def to_dataframes(self, session) -> Tuple:
        """(vertices df, edges df) for SQL-side analysis."""
        v = {"id": np.asarray(self.vertex_ids)}
        v.update({k: np.asarray(a) for k, a in self.vertex_attrs.items()})
        e = {"src": np.asarray(self.vertex_ids)[np.asarray(self.src)],
             "dst": np.asarray(self.vertex_ids)[np.asarray(self.dst)]}
        e.update({k: np.asarray(a) for k, a in self.edge_attrs.items()})
        import pandas as pd
        return (session.createDataFrame(pd.DataFrame(v)),
                session.createDataFrame(pd.DataFrame(e)))


def pregel(graph: Graph, initial_attrs: Dict[str, Array],
           vprog: Callable, send: Callable, merge: str = "sum",
           max_iterations: int = 20, initial_msg=None):
    """BSP iteration (`Pregel.scala:59`), vectorized.

    - `vprog(attrs, msgs, has_msg)` -> new vertex attr dict (applied every
      superstep; use `has_msg` to keep inactive vertices unchanged)
    - `send(src_attrs, dst_attrs, edge_attrs)` -> (msg_array, send_mask)
      per edge; masked edges send the reduction identity
    - `initial_msg`: delivered to EVERY vertex before the first superstep
      (vprog runs once with all has_msg true), per the reference contract
    - halts when no edge sends (all masks false) or after max_iterations

    Returns the final vertex attrs dict.
    """
    n = graph.num_vertices
    attrs = {k: jnp.asarray(v) for k, v in initial_attrs.items()}
    if initial_msg is not None:
        first = jnp.broadcast_to(jnp.asarray(initial_msg), (n,))
        attrs = vprog(dict(attrs), first, jnp.ones(n, bool))

    @jax.jit
    def superstep(attrs):
        srcs = {k: v[graph.src] for k, v in attrs.items()}
        dsts = {k: v[graph.dst] for k, v in attrs.items()}
        msg, send_mask = send(srcs, dsts, graph.edge_attrs)
        send_mask = jnp.asarray(send_mask, bool)
        if merge == "sum":
            masked = jnp.where(send_mask, msg, jnp.zeros((), msg.dtype))
        elif merge == "min":
            big = jnp.asarray(
                jnp.inf if jnp.issubdtype(msg.dtype, jnp.floating)
                else jnp.iinfo(msg.dtype).max, msg.dtype)
            masked = jnp.where(send_mask, msg, big)
        else:
            small = jnp.asarray(
                -jnp.inf if jnp.issubdtype(msg.dtype, jnp.floating)
                else jnp.iinfo(msg.dtype).min, msg.dtype)
            masked = jnp.where(send_mask, msg, small)
        msgs = _REDUCE[merge](masked, graph.dst, num_segments=n)
        has_msg = jops.segment_max(send_mask.astype(jnp.int32), graph.dst,
                                   num_segments=n) > 0
        new_attrs = vprog(dict(attrs), msgs, has_msg)
        active = jnp.sum(send_mask.astype(jnp.int64))
        return new_attrs, active

    for _ in range(max_iterations):
        attrs, active = superstep(attrs)
        if int(active) == 0:
            break
    return attrs
